"""Tenant-pack execution for the experiment queue (ISSUE 13).

`service/queue.py` runs scenario cells back-to-back; this module runs up
to E shape-compatible cells AT ONCE as one resident `*_mt` program
(fl/tenancy.py): per-tenant params/metrics carried as a stacked [E, ...]
pytree, per-tenant scalar knobs (seed, server LR, RLR threshold, attack
boost/schedule) as traced [E]-vectors, cohorts sampled/trained/
fault-injected/aggregated together, and every metrics boundary fanned
back out per tenant through ONE MetricsDrain into each tenant's own run
dir (the same run_name a solo run of that cell would use, so rows join).

Two layers:

- `plan_packs` — group a queue's cells into shape-compatible tenant
  packs using the compile-cache fingerprint's own field algebra
  (utils/compile_cache.tenant_pack_key — never an ad-hoc key list), with
  ineligible or shape-incompatible cells falling back to the serial path
  (a printed note per fallback, never a crash);
- `run_pack` — the pack engine: dataset/model/programs built ONCE, AOT
  bank adoption for the `*_mt` families, the chained dispatch loop, the
  tenant-stacked eval pair, and the per-tenant metrics fan-out.

Exactness: per-tenant results are parity-pinned against solo runs
(tests/test_tenancy.py — ulp-close floats, bitwise sign-rule params
where the megabatch precedent pins it; dataset content comes from the
pack's FIRST cell, which only matters for the seed-keyed synthetic
fallback). Checkpointing/heartbeat/spans are per-run facilities the pack
deliberately skips — queue cells are one-shot; run such cells solo.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
    tenancy as ftenancy)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
    FAULT_INFO_KEYS)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    monitor as health_monitor, sentinel as health_sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    attribution as obs_attribution, telemetry as obs_telemetry)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
    compile_cache)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.guards import (
    all_finite_device, finite_warn)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
    MetricsDrain, MetricsWriter, run_name)


class PackIneligible(ValueError):
    """A pack refusal discovered only at run_pack time, BEFORE any
    program build (e.g. the resolved host-sampled mode needs the
    dataset's byte size, which plan_packs never loads) — the queue
    catches it and routes the member cells to the serial path instead
    of recording a pack failure."""


def serial_reason(cfg) -> str:
    """Why a cell routes to the serial path instead of a tenant pack
    ('' = packable): the program-level refusals
    (fl/tenancy.ineligible_reason) plus the driver/runtime knobs that
    module deliberately does not read (it is in the fingerprint audit's
    program-read scope)."""
    reason = ftenancy.ineligible_reason(cfg)
    if reason:
        return reason
    if cfg.host_sampled == "on":
        return "host-sampled mode gathers shards per run; runs solo"
    if cfg.mesh != 1:
        return ("the tenant-pack ENGINE is single-device for now (the "
                "sharded *_mt family exists for the static contracts); "
                "runs solo")
    return ""


def plan_packs(base_cfg, cells: List[Dict[str, Any]], tenants: int,
               apply_overrides) -> List[Tuple[str, List[Dict[str, Any]]]]:
    """Group queue cells into ("pack", [cells...]) / ("serial", [cell])
    work items, preserving first-appearance order of each shape class.

    Cells are pack-eligible when fl/tenancy.ineligible_reason is empty
    AND their `tenant_pack_key` (the fingerprint-derived shape/program
    class) matches; groups chunk into packs of at most `tenants`, and a
    leftover singleton (or any incompatible cell) runs serial with a
    printed note. `apply_overrides(base_cfg, overrides)` is the queue's
    own cell->Config resolution, passed in so the two can never drift."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    items: List[Tuple[str, List[Dict[str, Any]]]] = []
    for cell in cells:
        try:
            cfg = apply_overrides(base_cfg, cell["overrides"])
            reason = serial_reason(cfg)
            key = None if reason else compile_cache.tenant_pack_key(cfg)
        except Exception as e:  # a broken cell still gets its queue row
            reason, key = f"{type(e).__name__}: {e}", None
        if key is None:
            print(f"[tenancy] cell {cell['name']!r} -> serial "
                  f"({reason})")
            items.append(("serial", [cell]))
            continue
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(cell)
    for key in order:
        group = groups[key]
        for i in range(0, len(group), tenants):
            pack = group[i:i + tenants]
            if len(pack) < 2:
                print(f"[tenancy] cell {pack[0]['name']!r} -> serial "
                      f"(no shape-compatible partner in this queue)")
                items.append(("serial", pack))
            else:
                items.append(("pack", pack))
    # keep queue-row order stable: sort items by their first cell's
    # position in the original list
    pos = {id(c): i for i, c in enumerate(cells)}
    items.sort(key=lambda it: pos[id(it[1][0])])
    return items


def _adopt(bank, cfg, family, jit_obj, example_args):
    """AOT-adopt one tenant family (the train.py _adopt_aot discipline:
    any failure falls back to the plain jit, which still warm-starts
    through the persistent XLA cache). Returns (fn_or_None, seconds)."""
    if bank is None:
        return None, 0.0
    try:
        compiled, hit, secs, _ = bank.get_or_compile(
            family, cfg, jit_obj, example_args)
    except Exception as e:
        print(f"[aot] {family}: falling back to jit "
              f"({type(e).__name__}: {e})")
        return None, 0.0
    print(f"[aot] {family}: "
          + ("loaded from cache" if hit else "compiled+banked")
          + f" in {secs:.1f}s")
    return compiled, secs


def run_pack(cfgs, names: Optional[List[str]] = None
             ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Run E shape-compatible cell configs as ONE tenant pack.

    Returns (per-tenant summary dicts in cell order, pack_info) where
    each summary matches the solo run-summary keys the queue consumes
    (service/queue.SUMMARY_KEYS) and pack_info carries the pack-level
    timing split (compile/AOT-acquisition vs steady seconds)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
        get_federated_data)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
        make_normalizer)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
        pad_eval_set)
    from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
        get_model, init_params, param_count)
    from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
        apply_rng_impl, dispatch_schedule)

    E = len(cfgs)
    if names is None:
        names = [f"tenant{e}" for e in range(E)]
    keys = {compile_cache.tenant_pack_key(c) for c in cfgs}
    if len(keys) != 1:
        raise ValueError(
            f"tenant pack mixes {len(keys)} shape/program classes — the "
            f"queue grouping (plan_packs) must only hand over cells with "
            f"one tenant_pack_key")
    rep = ftenancy.canonical_rep(cfgs[0].replace(tenants=E), cells=cfgs)
    ftenancy.check(rep)
    reason = serial_reason(cfgs[0])
    if reason:
        raise ValueError(f"tenant pack: {reason}")
    # cells must agree on rounds/snap (pack-key pinned) — the pack
    # advances every tenant in lockstep on one dispatch schedule
    rounds, snap = rep.rounds, rep.snap
    print(f"[tenancy] pack of {E} tenants x {rounds} rounds "
          f"({', '.join(names)})")
    apply_rng_impl(rep.rng_impl)
    bank = compile_cache.setup(rep)
    t0 = time.perf_counter()

    # dataset content comes from the pack's FIRST cell (seed-free for
    # disk-backed data; the synthetic fallback draws from its seed —
    # documented exactness semantics, README "Multi-tenant sweeps")
    fed = get_federated_data(cfgs[0])
    if compile_cache.is_host_mode(rep, fed):
        # host_sampled='auto' resolves against the loaded data's byte
        # size — the solo driver would route these cells through the
        # host-sampled families, but the pack binds the full train
        # stacks as device-resident jit arguments
        raise PackIneligible(
            f"host-sampled mode resolves ON for this dataset "
            f"({fed.train.images.nbytes / 1e9:.2f} GB train stack "
            f"exceeds the device-resident budget); running cells solo")
    model = get_model(rep.data, rep.model_arch, rep.dtype, remat=rep.remat,
                     remat_policy=rep.remat_policy)
    norm = make_normalizer(fed.mean, fed.std, fed.raw_is_normalized)
    arrays = (jnp.asarray(fed.train.images), jnp.asarray(fed.train.labels),
              jnp.asarray(fed.train.sizes))
    image_shape = fed.train.images.shape[2:]
    # per-tenant init from each tenant's OWN seed — bitwise the solo init
    params_E = ftenancy.stack_params([
        init_params(model, image_shape, jax.random.PRNGKey(c.seed))
        for c in cfgs])
    n_params = param_count(ftenancy.tenant_slice(params_E, 0))
    base_keys_E = jnp.stack([jax.random.PRNGKey(c.seed) for c in cfgs])
    knobs = jax.tree_util.tree_map(jnp.asarray,
                                   ftenancy.knob_vectors(cfgs))

    chain_n = compile_cache.chain_budget(rep)
    round_fn = ftenancy.make_tenant_round_fn(rep, model, norm, *arrays)
    chained_fn = (ftenancy.make_tenant_chained_fn(rep, model, norm,
                                                  *arrays)
                  if chain_n > 1 else None)
    eval_fn = ftenancy.make_tenant_eval_fn(model, norm, rep.n_classes)
    val = tuple(map(jnp.asarray, pad_eval_set(
        fed.val_images, fed.val_labels, rep.eval_bs)))
    pval = tuple(map(jnp.asarray, pad_eval_set(
        fed.pval_images, fed.pval_labels, rep.eval_bs)))

    # --- AOT adoption of the *_mt families (warm packs skip XLA) ---
    compile_s = 0.0
    ab = compile_cache.abstractify
    pE_aval, kE_aval = ab(params_E), ab(base_keys_E)
    knob_aval = ab(knobs)
    data_avals = ab(arrays)
    rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
    fn, secs = _adopt(bank, rep, round_fn.family, round_fn.jitted,
                      (pE_aval, kE_aval, rnd_aval, knob_aval) + data_avals)
    compile_s += secs
    if fn is not None:
        data = round_fn.data

        def round_fn(pE, kE, rnd, kn, _fn=fn, _data=data):  # noqa: E731
            return _fn(pE, kE, rnd, kn, *_data)
    if chained_fn is not None:
        ids_aval = jax.ShapeDtypeStruct((chain_n,), jnp.int32)
        fn, secs = _adopt(bank, rep, chained_fn.family, chained_fn.jitted,
                          (pE_aval, kE_aval, ids_aval, knob_aval)
                          + data_avals)
        compile_s += secs
        if fn is not None:
            data = chained_fn.data

            def chained_fn(pE, kE, ids, kn, _fn=fn, _data=data):
                return _fn(pE, kE, ids, kn, *_data)
    eval_val_fn = eval_pval_fn = eval_fn
    fn, secs = _adopt(bank, rep, "eval_val_mt", eval_fn,
                      (pE_aval,) + ab(val))
    compile_s += secs
    if fn is not None:
        eval_val_fn = fn
    fn, secs = _adopt(bank, rep, "eval_poison_mt", eval_fn,
                      (pE_aval,) + ab(pval))
    compile_s += secs
    if fn is not None:
        eval_pval_fn = fn

    # --- per-tenant metrics plumbing: one writer per cell's run dir ---
    writers = [MetricsWriter(c.log_dir, run_name(c), c.tensorboard)
               for c in cfgs]
    drain = (MetricsDrain() if rep.async_metrics else None)
    # per-tenant tel_* filter: series this tenant's SOLO twin would emit
    tel_allowed = [obs_telemetry.telemetry_keys(c) for c in cfgs]
    # scalar health lanes only — the solo twin's boundary_keys
    # discipline: the [E, m] hlth_agent_bad suspect vector is ladder
    # evidence and must never ride the per-boundary device->host fetch
    hlth_boundary = set(health_sentinel.boundary_keys(cfgs[0]))
    state = {"cum_poison": [0.0] * E, "summaries": [{} for _ in range(E)],
             "t_steady": None, "r_steady": 0,
             "t_steady_end": None, "r_steady_end": 0,
             # per-tenant health-EMA baselines (health/sentinel.py): each
             # tenant's Health/Loss_Z judges against ITS OWN history,
             # exactly like its solo twin
             "health_ema": [None] * E}
    fold = jax.jit(jax.vmap(jax.random.fold_in, in_axes=(0, None)))

    def emit(vals, ernd, rounds_done_now, elapsed):
        """One eval boundary's per-tenant fan-out — runs on the drain
        thread (async) or inline (sync); mirrors the solo
        train._emit_eval_body row order so tenant streams byte-compare
        to solo runs modulo wall-clock rows."""
        lane_on = "hlth_nonfinite" in vals
        if not lane_on:
            # --health off keeps the historical pack-level endpoint
            finite_warn(vals["finite"], where=f"pack round {ernd}")
        now = time.perf_counter()
        for e, (writer, cfg) in enumerate(zip(writers, cfgs,
                                              strict=True)):
            report = None
            if lane_on:
                # per-tenant health lane: the solo twin's assess/emit/
                # enforce (train._emit_eval_body) sliced per tenant —
                # Health/* rows land BEFORE Validation/*, the solo row
                # order, so tenant streams keep byte-parity with solo
                # runs. Each tenant is judged on ITS OWN committed-params
                # bit, not the pack-wide one (one diverging tenant must
                # not flag its pack-mates).
                hvals = {"finite":
                         float(vals["hlth_params_finite"][e]) >= 1.0,
                         "train_loss": float(vals["train_loss"][e])}
                for k in health_sentinel.boundary_keys(cfg):
                    if k in vals:
                        hvals[k] = float(vals[k][e])
                report = health_monitor.assess(
                    cfg, state["health_ema"][e], hvals)
                health_monitor.emit_rows(writer, report, ernd)
                health_monitor.enforce(
                    cfg, report, where=f"pack round {ernd} tenant {e}")
            val_loss = float(vals["val_loss"][e])
            val_acc = float(vals["val_acc"][e])
            poison_loss = float(vals["poison_loss"][e])
            poison_acc = float(vals["poison_acc"][e])
            state["cum_poison"][e] += poison_acc
            writer.scalar("Validation/Loss", val_loss, ernd)
            writer.scalar("Validation/Accuracy", val_acc, ernd)
            writer.scalar("Poison/Base_Class_Accuracy",
                          float(vals["base_acc"][e]), ernd)
            writer.scalar("Poison/Poison_Accuracy", poison_acc, ernd)
            writer.scalar("Poison/Poison_Loss", poison_loss, ernd)
            writer.scalar("Poison/Cumulative_Poison_Accuracy_Mean",
                          state["cum_poison"][e] / ernd, ernd)
            writer.scalar("Train/Loss", float(vals["train_loss"][e]),
                          ernd)
            if "fault_voters" in vals:
                writer.scalar("Faults/Dropped",
                              float(vals["fault_dropped"][e]), ernd)
                writer.scalar("Faults/Straggled",
                              float(vals["fault_straggled"][e]), ernd)
                writer.scalar("Faults/Effective_Voters",
                              float(vals["fault_voters"][e]), ernd)
            if "churn_away" in vals:
                writer.scalar("Churn/Sampled_Away",
                              float(vals["churn_away"][e]), ernd)
            tel = obs_telemetry.tenant_rows(vals, e,
                                            allowed=tel_allowed[e])
            obs_telemetry.emit_scalars(writer, tel, ernd)
            writer.scalar("Throughput/Rounds_Per_Sec",
                          rounds_done_now / elapsed, ernd)
            if (state["t_steady"] is not None
                    and rounds_done_now > state["r_steady"]):
                writer.scalar("Throughput/Steady_Rounds_Per_Sec",
                              (rounds_done_now - state["r_steady"])
                              / (now - state["t_steady"]), ernd)
            summary = {
                "round": ernd, "val_loss": val_loss, "val_acc": val_acc,
                "poison_loss": poison_loss, "poison_acc": poison_acc,
                "rounds_per_sec": rounds_done_now / elapsed}
            if tel:
                summary["defense"] = obs_telemetry.host_summary(tel)
            if report is not None and report["rows"]:
                # the lane's verdict as data: queue rows
                # (service/queue.SUMMARY_KEYS) record per-cell health —
                # the SAME schema as the solo path's summary (train.py
                # _emit_eval_body), so packed-vs-serial rows stay
                # structurally identical
                summary["health"] = {k: float(v)
                                     for k, v in report["rows"].items()}
                # EMA commits LAST (the solo twin's discipline)
                state["health_ema"][e] = report["new_state"]
            state["summaries"][e] = summary
            writer.flush()
        if state["t_steady"] is None:
            state["t_steady"] = now
            state["r_steady"] = rounds_done_now
        else:
            state["t_steady_end"] = now
            state["r_steady_end"] = rounds_done_now

    # --- the dispatch loop: the solo schedule, E experiments per unit ---
    rounds_done = 0
    loop_ok = False
    t_loop = time.perf_counter()
    try:
        for unit in dispatch_schedule(0, rounds, snap, chain_n, False,
                                      chained_fn is not None):
            if len(unit) > 1:
                ids = jnp.arange(unit[0], unit[-1] + 1)
                params_E, stacked = chained_fn(params_E, base_keys_E, ids,
                                               knobs)
                rnd = unit[-1]
                info = {k: v[-1] for k, v in stacked.items()}
            else:
                rnd = unit[0]
                keys_E = fold(base_keys_E, rnd)
                params_E, info = round_fn(params_E, keys_E,
                                          jnp.int32(rnd), knobs)
            rounds_done += len(unit)
            if rnd % snap == 0:
                vals = {"finite": all_finite_device(params_E)}
                val_loss_d, val_acc_d, per_class_d = eval_val_fn(
                    params_E, *val)
                poison_loss_d, poison_acc_d, _ = eval_pval_fn(
                    params_E, *pval)
                vals.update(val_loss=val_loss_d, val_acc=val_acc_d,
                            base_acc=per_class_d[:, rep.base_class],
                            poison_loss=poison_loss_d,
                            poison_acc=poison_acc_d,
                            train_loss=info["train_loss"])
                if "fault_voters" in info:
                    vals.update({k: info[k] for k in FAULT_INFO_KEYS})
                if "churn_away" in info:
                    vals["churn_away"] = info["churn_away"]
                vals.update({k: info[k] for k in info
                             if k.startswith("tel_")
                             or k in hlth_boundary})
                elapsed = time.perf_counter() - t_loop
                if drain is not None:
                    drain.submit(emit, vals, rnd, rounds_done, elapsed)
                else:
                    vals = jax.device_get(vals)  # static: ok(host-sync)
                    emit(vals, rnd, rounds_done, elapsed)
        if drain is not None:
            drain.flush()
        loop_ok = True
    finally:
        if drain is not None:
            drain.close(raise_errors=False)
        if not loop_ok:
            # a failed pack still flushes+releases every tenant's
            # metrics handle (the queue records the failure and moves
            # on; the success path closes writers after the memory
            # rows below — close() is not re-entrant)
            for writer in writers:
                try:
                    writer.close()
                except Exception:
                    pass

    elapsed = time.perf_counter() - t_loop
    wall = time.perf_counter() - t0
    pack_rps = rounds_done / max(elapsed, 1e-9)
    steady_rps = None
    if (state["t_steady"] is not None
            and state["t_steady_end"] is not None
            and state["r_steady_end"] > state["r_steady"]):
        steady_rps = ((state["r_steady_end"] - state["r_steady"])
                      / max(state["t_steady_end"] - state["t_steady"],
                            1e-9))
    mem = obs_attribution.memory_watermarks()
    mem.update(obs_attribution.host_watermarks())
    summaries = []
    for e, (writer, cfg) in enumerate(zip(writers, cfgs, strict=True)):
        if mem:
            for tag, v in obs_attribution.memory_rows(mem):
                writer.scalar(tag, v, rounds)
        writer.close()
        summary = dict(state["summaries"][e])
        summary.setdefault("round", rounds)
        summary["rounds_per_sec"] = pack_rps
        if steady_rps is not None:
            summary["steady_rounds_per_sec"] = steady_rps
        summary["params"] = n_params
        summaries.append(summary)
    pack_info = {"tenants": E, "rounds": rounds,
                 "wall_s": round(wall, 3),
                 "compile_s": round(compile_s, 3),
                 "rounds_per_sec": round(pack_rps, 4)}
    if steady_rps is not None:
        pack_info["steady_rounds_per_sec"] = round(steady_rps, 4)
    print(f"[tenancy] pack done: {E} tenants x {rounds} rounds in "
          f"{wall:.1f}s ({pack_rps:.2f} pack-rounds/sec)")
    return summaries, pack_info
