"""Tenant-pack execution for the experiment queue (ISSUE 13 + 16).

`service/queue.py` runs scenario cells back-to-back; this module runs up
to E shape-compatible cells AT ONCE as one resident `*_mt` program
(fl/tenancy.py): per-tenant params/metrics carried as a stacked [E, ...]
pytree, per-tenant scalar knobs (seed, server LR, RLR threshold, attack
boost/schedule, slot clock) as traced [E]-vectors, cohorts sampled/
trained/fault-injected/aggregated together, and every metrics boundary
fanned back out per tenant through ONE MetricsDrain into each tenant's
own run dir (the same run_name a solo run of that cell would use, so
rows join).

Three layers:

- `plan_packs` — group a queue's cells into shape-compatible tenant
  packs using the compile-cache fingerprint's own field algebra
  (utils/compile_cache.tenant_pack_key — never an ad-hoc key list), with
  ineligible or shape-incompatible cells falling back to the serial path
  (a printed note per fallback, never a crash);
- `PackEngine` — the resident engine: dataset/model/programs built ONCE
  for a shape class, AOT bank adoption for the `*_mt` families, the
  per-unit dispatch + eval-boundary fan-out, and the per-SLOT state a
  scheduler needs (load/finalize/fail a tenant slot mid-run). The engine
  covers the vmap, sharded-mesh and cohort-sampled pack paths (ISSUE 16
  gaps 1-3: buffered carry stacked [E, ...], the `*_mt` shard_map
  families on a live mesh, one shared bank gather per cohort round);
- `run_pack` — the FIFO wrapper: build an engine, run every tenant start
  to finish in lockstep (offsets 0), return per-tenant summaries — the
  PR-13 semantics, byte-for-byte.

The bin-packing scheduler (service/scheduler.py) drives the SAME engine
with per-slot `rnd_offset`s: a slot whose cell completed (or was
evicted on a health incident) is reloaded with the next queued cell at
offset = -pack_round, so its key streams and schedule gates replay the
solo program exactly while the rest of the pack keeps training.

Exactness: per-tenant results are parity-pinned against solo runs
(tests/test_tenancy.py — ulp-close floats, bitwise sign-rule params
where the megabatch precedent pins it; dataset content comes from the
pack's FIRST cell, which only matters for the seed-keyed synthetic
fallback). Checkpointing/heartbeat/spans are per-run facilities the pack
deliberately skips — queue cells are one-shot; run such cells solo.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
    buffered, tenancy as ftenancy)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
    FAULT_INFO_KEYS)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    monitor as health_monitor, sentinel as health_sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    attribution as obs_attribution, events as obs_events,
    reputation as obs_reputation, telemetry as obs_telemetry)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
    compile_cache)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.guards import (
    all_finite_device, finite_warn)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
    MetricsDrain, MetricsWriter, run_name)


class PackIneligible(ValueError):
    """A pack refusal discovered only at run_pack time, BEFORE any
    program build (e.g. the resolved host-sampled mode needs the
    dataset's byte size, which plan_packs never loads) — the queue
    catches it and routes the member cells to the serial path instead
    of recording a pack failure."""


def serial_reason(cfg) -> str:
    """Why a cell routes to the serial path instead of a tenant pack
    ('' = packable): the program-level refusals
    (fl/tenancy.ineligible_reason) plus the driver/runtime knobs that
    module deliberately does not read (it is in the fingerprint audit's
    program-read scope). The PR-13 mesh refusal is retired: the engine
    resolves --mesh like the solo driver and dispatches the sharded
    `*_mt` families (cohort packs ignore the mesh request — there is no
    sharded cohort tenant family — with a printed note)."""
    reason = ftenancy.ineligible_reason(cfg)
    if reason:
        return reason
    if cfg.host_sampled == "on":
        return "host-sampled mode gathers shards per run; runs solo"
    return ""


def plan_packs(base_cfg, cells: List[Dict[str, Any]], tenants: int,
               apply_overrides) -> List[Tuple[str, List[Dict[str, Any]]]]:
    """Group queue cells into ("pack", [cells...]) / ("serial", [cell])
    work items, preserving first-appearance order of each shape class.

    Cells are pack-eligible when fl/tenancy.ineligible_reason is empty
    AND their `tenant_pack_key` (the fingerprint-derived shape/program
    class) matches; groups chunk into packs of at most `tenants`, and a
    leftover singleton (or any incompatible cell) runs serial with a
    printed note. `apply_overrides(base_cfg, overrides)` is the queue's
    own cell->Config resolution, passed in so the two can never drift."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    items: List[Tuple[str, List[Dict[str, Any]]]] = []
    for cell in cells:
        try:
            cfg = apply_overrides(base_cfg, cell["overrides"])
            reason = serial_reason(cfg)
            key = None if reason else compile_cache.tenant_pack_key(cfg)
        except Exception as e:  # a broken cell still gets its queue row
            reason, key = f"{type(e).__name__}: {e}", None
        if key is None:
            print(f"[tenancy] cell {cell['name']!r} -> serial "
                  f"({reason})")
            items.append(("serial", [cell]))
            continue
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(cell)
    for key in order:
        group = groups[key]
        for i in range(0, len(group), tenants):
            pack = group[i:i + tenants]
            if len(pack) < 2:
                print(f"[tenancy] cell {pack[0]['name']!r} -> serial "
                      f"(no shape-compatible partner in this queue)")
                items.append(("serial", pack))
            else:
                items.append(("pack", pack))
    # keep queue-row order stable: sort items by their first cell's
    # position in the original list
    pos = {id(c): i for i, c in enumerate(cells)}
    items.sort(key=lambda it: pos[id(it[1][0])])
    return items


def _adopt(bank, cfg, family, jit_obj, example_args):
    """AOT-adopt one tenant family (the train.py _adopt_aot discipline:
    any failure falls back to the plain jit, which still warm-starts
    through the persistent XLA cache). Returns (fn_or_None, seconds)."""
    if bank is None:
        return None, 0.0
    try:
        compiled, hit, secs, _ = bank.get_or_compile(
            family, cfg, jit_obj, example_args)
    except Exception as e:
        print(f"[aot] {family}: falling back to jit "
              f"({type(e).__name__}: {e})")
        return None, 0.0
    print(f"[aot] {family}: "
          + ("loaded from cache" if hit else "compiled+banked")
          + f" in {secs:.1f}s")
    return compiled, secs


class _Slot:
    """One resident tenant slot's host-side state: the cell it is
    running, its clock offset, its metrics writer and the per-tenant
    emission state the solo twin would keep."""

    def __init__(self, cfg, name: str, offset: int = 0,
                 writer: Optional[MetricsWriter] = None):
        self.cfg = cfg
        self.name = name
        self.offset = int(offset)
        self.writer = writer
        self.active = writer is not None
        self.tel_allowed = (obs_telemetry.telemetry_keys(cfg)
                            if self.active else [])
        self.cum_poison = 0.0
        self.health_ema = None
        # per-tenant suspicion ledger (obs/reputation.py) — assigned by
        # the engine when the pack program carries the rep_agree lane
        self.rep_tracker = None
        self.summary: Dict[str, Any] = {}
        self.error: Optional[BaseException] = None


class PackEngine:
    """The resident tenant-pack engine (see module docstring).

    `run_pack` (FIFO) and `service/scheduler.py` (bin-packed, backfilled)
    both drive this object; everything built in __init__ — dataset,
    model, round/chained/eval programs, AOT adoption, the stacked carry —
    is built ONCE per shape class and survives slot reloads.

    `evict_on_anomaly=True` (the scheduler) turns a per-tenant health
    enforcement failure into a slot eviction (the boundary returns the
    failed slots) instead of failing the whole pack — the FIFO path
    keeps the historical fail-the-pack semantics."""

    def __init__(self, cfgs, names: Optional[List[str]] = None,
                 offsets: Optional[List[int]] = None,
                 evict_on_anomaly: bool = False):
        from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
            get_federated_data)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
            make_normalizer)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
            pad_eval_set)
        from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
            get_model, init_params, param_count)
        from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
            apply_rng_impl)

        E = len(cfgs)
        if names is None:
            names = [f"tenant{e}" for e in range(E)]
        if offsets is None:
            offsets = [0] * E
        keys = {compile_cache.tenant_pack_key(c) for c in cfgs}
        if len(keys) != 1:
            raise ValueError(
                f"tenant pack mixes {len(keys)} shape/program classes — "
                f"the queue grouping (plan_packs) must only hand over "
                f"cells with one tenant_pack_key")
        self.pack_key = next(iter(keys))
        rep = ftenancy.canonical_rep(cfgs[0].replace(tenants=E),
                                     cells=cfgs)
        ftenancy.check(rep)
        reason = serial_reason(cfgs[0])
        if reason:
            raise ValueError(f"tenant pack: {reason}")
        self.rep = rep
        self.width = E
        self.evict_on_anomaly = evict_on_anomaly
        # cells must agree on rounds/snap (pack-key pinned) — the pack
        # advances every tenant in lockstep on one dispatch schedule
        self.rounds, self.snap = rep.rounds, rep.snap
        apply_rng_impl(rep.rng_impl)
        bank = compile_cache.setup(rep)
        self.t0 = time.perf_counter()

        # dataset content comes from the pack's FIRST cell (seed-free for
        # disk-backed data; the synthetic fallback draws from its seed —
        # documented exactness semantics, README "Multi-tenant sweeps")
        self.cohort = compile_cache.is_cohort_mode(rep)
        if self.cohort:
            from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
                get_cohort_data)
            fed = get_cohort_data(cfgs[0])
        else:
            fed = get_federated_data(cfgs[0])
            if compile_cache.is_host_mode(rep, fed):
                # host_sampled='auto' resolves against the loaded data's
                # byte size — the solo driver would route these cells
                # through the host-sampled families, but the pack binds
                # the full train stacks as device-resident jit arguments
                raise PackIneligible(
                    f"host-sampled mode resolves ON for this dataset "
                    f"({fed.train.images.nbytes / 1e9:.2f} GB train "
                    f"stack exceeds the device-resident budget); "
                    f"running cells solo")
        self.fed = fed
        model = get_model(rep.data, rep.model_arch, rep.dtype,
                          remat=rep.remat, remat_policy=rep.remat_policy)
        norm = make_normalizer(fed.mean, fed.std, fed.raw_is_normalized)
        self.model = model
        self.image_shape = fed.train.images.shape[2:]
        m = rep.agents_per_round

        # --- mesh resolution (the solo driver's rules) ---
        self.n_mesh = 1
        mesh = None
        if rep.mesh != 1 and not self.cohort:
            from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
                make_mesh, pick_agent_mesh_size)
            self.n_mesh = pick_agent_mesh_size(rep.mesh, m)
            if self.n_mesh > 1:
                mesh = make_mesh(self.n_mesh)
                print(f"[tenancy] sharded pack: {self.n_mesh} devices on "
                      f"the `agents` axis ({m // self.n_mesh} "
                      f"agents/device), tenant axis folded in-shard")
            else:
                print(f"[tenancy] no device count <= "
                      f"{rep.mesh or 'all'} divides m={m}; --mesh "
                      f"request ignored")
        elif rep.mesh != 1 and self.cohort:
            print("[tenancy] cohort packs run the vmap tenant family; "
                  "--mesh request ignored (no sharded cohort tenant "
                  "family)")

        # --- per-slot device state ---
        self.is_async = buffered.is_buffered(rep)
        params_E = ftenancy.stack_params([
            init_params(model, self.image_shape,
                        jax.random.PRNGKey(c.seed))
            for c in cfgs])
        self.n_params = param_count(ftenancy.tenant_slice(params_E, 0))
        if self.is_async:
            astate_E = ftenancy.stack_params([
                buffered.init_state(
                    rep,
                    ftenancy.tenant_slice(jax.device_get(params_E), e),
                    per_bin=(self.n_mesh == 1))
                for e in range(E)])
            self.carry = (params_E, astate_E)
        else:
            self.carry = params_E
        self.base_keys_E = jnp.stack(
            [jax.random.PRNGKey(c.seed) for c in cfgs])
        self.knobs = jax.tree_util.tree_map(
            jnp.asarray, ftenancy.knob_vectors(cfgs, offsets))
        # per-tenant key fold at the EFFECTIVE round (the solo driver's
        # fold_in(base_key, rnd), on each slot's own clock)
        self._fold = jax.jit(jax.vmap(
            lambda k, off, r: jax.random.fold_in(k, r + off),
            in_axes=(0, 0, None)))

        # --- programs + AOT adoption (warm packs skip XLA) ---
        arrays = (jnp.asarray(fed.train.images),
                  jnp.asarray(fed.train.labels),
                  jnp.asarray(fed.train.sizes))
        self.chain_n = (compile_cache.chain_budget(rep)
                        if not self.cohort and self.n_mesh == 1 else 1)
        self.compile_s = 0.0
        ab = compile_cache.abstractify
        carryE_aval = ab(self.carry)
        pE_aval = carryE_aval[0] if self.is_async else carryE_aval
        kE_aval = ab(self.base_keys_E)
        knob_aval = ab(self.knobs)
        rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
        self.chained_fn = None
        self._gather_rows = None
        self._prefetch: Optional[Tuple[int, Any]] = None
        self._exec = None
        if self.cohort:
            # ONE shared bank gather per round serves the whole pack
            # (ISSUE 16 gap 3): the cohort draw is cohort_seed-driven and
            # identical across tenants — scheduler admission keeps every
            # offset 0 so the shared draw stays shared
            if any(o != 0 for o in offsets):
                raise ValueError(
                    "cohort packs admit no clock skew (the shared bank "
                    "gather serves one draw); offsets must all be 0")
            if getattr(fed, "bank", None) is not None:
                self._gather_rows = fed.gather_cohort
                print(f"[tenancy] cohort pack: population "
                      f"{rep.num_agents:,} -> {m}-client cohorts, one "
                      f"shared gather for {E} tenants/round")
            else:
                self._gather_rows = lambda ids: (
                    fed.train.images[ids], fed.train.labels[ids],
                    fed.train.sizes[ids])
            self._exec = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pack-prefetch")
            round_fn = ftenancy.make_tenant_cohort_round_fn(rep, model,
                                                            norm)
            shard_avals = tuple(
                jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
                for a in ab(arrays))
            fn, secs = _adopt(
                bank, rep, round_fn.family, round_fn.jitted,
                (carryE_aval, kE_aval, rnd_aval, knob_aval) + shard_avals)
            self.compile_s += secs
            self.round_fn = (round_fn if fn is None else fn)
        elif self.n_mesh > 1:
            # mesh executables embed the live mesh — never AOT-banked
            # (the solo driver's rule); the persistent XLA cache still
            # warm-starts them
            from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                make_sharded_round_fn_mt)
            self.round_fn = make_sharded_round_fn_mt(rep, model, norm,
                                                     mesh, *arrays)
        else:
            round_fn = ftenancy.make_tenant_round_fn(rep, model, norm,
                                                     *arrays)
            data_avals = ab(arrays)
            fn, secs = _adopt(
                bank, rep, round_fn.family, round_fn.jitted,
                (carryE_aval, kE_aval, rnd_aval, knob_aval) + data_avals)
            self.compile_s += secs
            if fn is not None:
                data = round_fn.data

                def round_fn(cE, kE, rnd, kn, _fn=fn, _data=data):
                    return _fn(cE, kE, rnd, kn, *_data)
            self.round_fn = round_fn
            if self.chain_n > 1:
                chained_fn = ftenancy.make_tenant_chained_fn(
                    rep, model, norm, *arrays)
                ids_aval = jax.ShapeDtypeStruct((self.chain_n,),
                                                jnp.int32)
                fn, secs = _adopt(
                    bank, rep, chained_fn.family, chained_fn.jitted,
                    (carryE_aval, kE_aval, ids_aval, knob_aval)
                    + data_avals)
                self.compile_s += secs
                if fn is not None:
                    data = chained_fn.data

                    def chained_fn(cE, kE, ids, kn, _fn=fn, _data=data):
                        return _fn(cE, kE, ids, kn, *_data)
                self.chained_fn = chained_fn

        eval_fn = ftenancy.make_tenant_eval_fn(model, norm, rep.n_classes)
        self.val = tuple(map(jnp.asarray, pad_eval_set(
            fed.val_images, fed.val_labels, rep.eval_bs)))
        self.pval = tuple(map(jnp.asarray, pad_eval_set(
            fed.pval_images, fed.pval_labels, rep.eval_bs)))
        self.eval_val_fn = self.eval_pval_fn = eval_fn
        fn, secs = _adopt(bank, rep, "eval_val_mt", eval_fn,
                          (pE_aval,) + ab(self.val))
        self.compile_s += secs
        if fn is not None:
            self.eval_val_fn = fn
        fn, secs = _adopt(bank, rep, "eval_poison_mt", eval_fn,
                          (pE_aval,) + ab(self.pval))
        self.compile_s += secs
        if fn is not None:
            self.eval_pval_fn = fn

        # --- per-tenant metrics plumbing: one writer per cell's run dir
        self.slots = [
            _Slot(cfg, name, offsets[e],
                  MetricsWriter(cfg.log_dir, run_name(cfg),
                                cfg.tensorboard))
            for e, (cfg, name) in enumerate(zip(cfgs, names, strict=True))]
        self.drain = (MetricsDrain()
                      if rep.async_metrics and not evict_on_anomaly
                      else None)
        # scalar health lanes only — the solo twin's boundary_keys
        # discipline: the [E, m] hlth_agent_bad suspect vector is ladder
        # evidence and must never ride the per-boundary fetch
        self.hlth_boundary = set(health_sentinel.boundary_keys(cfgs[0]))
        # per-tenant suspicion ledgers: the pack program's [E, m]
        # rep_agree lane fans out one tracker per cell — the solo twin's
        # longitudinal state, sliced on the tenant axis at the boundary
        self._rep_on = obs_reputation.reputation_on(rep)
        self._rep_pending: List[Any] = []
        if self._rep_on:
            for slot in self.slots:
                if slot.active:
                    slot.rep_tracker = (
                        obs_reputation.ReputationTracker.for_config(
                            slot.cfg, population=slot.cfg.num_agents))
        self.t_steady = None
        self.r_steady = 0
        self.t_steady_end = None
        self.r_steady_end = 0

    # ---------------------------------------------------------- slots ---

    def active_slots(self) -> List[int]:
        return [e for e, s in enumerate(self.slots) if s.active]

    def _refresh_knobs(self) -> None:
        self.knobs = jax.tree_util.tree_map(
            jnp.asarray,
            ftenancy.knob_vectors([s.cfg for s in self.slots],
                                  [s.offset for s in self.slots]))

    def load_slot(self, e: int, cfg, name: str, offset: int) -> None:
        """Backfill slot e with a fresh cell at clock offset `offset`
        (= -pack_round, so the cell's effective round counts 1..rounds):
        per-tenant params/buffer re-initialized from the cell's own seed
        — bitwise the solo init — via a functional [e]-indexed update of
        the stacked carry; knobs rebuilt host-side."""
        from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
            init_params)
        if self.cohort:
            raise ValueError("cohort packs admit no mid-run backfill "
                             "(the shared gather serves one draw)")
        params = init_params(self.model, self.image_shape,
                             jax.random.PRNGKey(cfg.seed))
        set_e = lambda P, p: P.at[e].set(jnp.asarray(p, P.dtype))  # noqa: E731
        if self.is_async:
            pE, aE = self.carry
            astate = buffered.init_state(self.rep, params,
                                         per_bin=(self.n_mesh == 1))
            self.carry = (jax.tree_util.tree_map(set_e, pE, params),
                          jax.tree_util.tree_map(set_e, aE, astate))
        else:
            self.carry = jax.tree_util.tree_map(set_e, self.carry, params)
        self.base_keys_E = self.base_keys_E.at[e].set(
            jax.random.PRNGKey(cfg.seed))
        self.slots[e] = _Slot(cfg, name, offset,
                              MetricsWriter(cfg.log_dir, run_name(cfg),
                                            cfg.tensorboard))
        if self._rep_on:
            # a backfilled cell starts its suspicion ledger fresh — the
            # solo twin's state at its round 0
            self.slots[e].rep_tracker = (
                obs_reputation.ReputationTracker.for_config(
                    cfg, population=cfg.num_agents))
        self._refresh_knobs()

    def finalize_slot(self, e: int) -> Dict[str, Any]:
        """Close out a COMPLETED slot: memory rows + writer close, then
        the solo-schema summary (service/queue.SUMMARY_KEYS)."""
        slot = self.slots[e]
        mem = obs_attribution.memory_watermarks()
        mem.update(obs_attribution.host_watermarks())
        if mem:
            for tag, v in obs_attribution.memory_rows(mem):
                slot.writer.scalar(tag, v, self.rounds)
        slot.writer.close()
        slot.active = False
        summary = dict(slot.summary)
        summary.setdefault("round", self.rounds)
        summary["params"] = self.n_params
        return summary

    def fail_slot(self, e: int, error: BaseException) -> None:
        """Evict a slot on a health incident / per-tenant failure:
        record-and-skip (the queue rows the failure; pack-mates keep
        training)."""
        slot = self.slots[e]
        slot.error = error
        slot.active = False
        try:
            slot.writer.close()
        except Exception:
            pass

    def idle_slot(self, e: int) -> None:
        """Mark a slot idle (nothing left to backfill): it keeps
        computing masked garbage on the pack clock — the occupancy
        metric, not a mask, accounts for the waste."""
        self.slots[e].active = False

    # ------------------------------------------------------- dispatch ---

    def _cohort_data(self, rnd: int):
        """The round's shared [m, ...] cohort rows — host-mirrored draw
        (data/cohort.sample_cohort_host, bit-identical to the in-program
        draw) + ONE indexed gather for the whole pack, one round ahead on
        the prefetch thread."""
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            cohort as cohort_mod)

        def gather(r):
            ids, _active = cohort_mod.sample_cohort_host(self.rep, r)
            return tuple(map(jnp.asarray, self._gather_rows(ids)))

        if self._prefetch is not None and self._prefetch[0] == rnd:
            data = self._prefetch[1].result()
        else:
            data = gather(rnd)
        self._prefetch = (rnd + 1, self._exec.submit(gather, rnd + 1))
        return data

    def dispatch_unit(self, unit) -> Tuple[int, Dict[str, Any]]:
        """Advance the pack clock over one schedule unit (a chained
        block or a single round); returns (pack_round, last-round info)."""
        if len(unit) > 1:
            ids = jnp.arange(unit[0], unit[-1] + 1)
            self.carry, stacked = self.chained_fn(
                self.carry, self.base_keys_E, ids, self.knobs)
            if self._rep_on and "rep_agree" in stacked:
                # [chain, E, m] agreement + norm rows + the matching
                # stacked client ids — sliced per tenant at the boundary
                # fan-out
                self._rep_pending.append((tuple(unit), stacked["sampled"],
                                          stacked["rep_agree"],
                                          stacked["rep_norm"]))
            return unit[-1], {k: v[-1] for k, v in stacked.items()}
        rnd = unit[0]
        keys_E = self._fold(self.base_keys_E, self.knobs.rnd_offset, rnd)
        if self.cohort:
            self.carry, info = self.round_fn(
                self.carry, keys_E, jnp.int32(rnd), self.knobs,
                *self._cohort_data(rnd))
        else:
            self.carry, info = self.round_fn(self.carry, keys_E,
                                             jnp.int32(rnd), self.knobs)
        if self._rep_on and "rep_agree" in info:
            self._rep_pending.append(((rnd,), info["sampled"],
                                      info["rep_agree"],
                                      info["rep_norm"]))
        return rnd, info

    def params_E(self):
        return self.carry[0] if self.is_async else self.carry

    def eval_boundary(self, rnd: int, info, rounds_done: int,
                      elapsed: float) -> Dict[int, BaseException]:
        """One eval boundary: the tenant-stacked eval pair + per-slot
        fan-out. Returns {slot: error} for slots whose health enforcement
        failed (only ever non-empty with evict_on_anomaly; the FIFO path
        re-raises instead)."""
        params_E = self.params_E()
        vals = {"finite": all_finite_device(params_E)}
        val_loss_d, val_acc_d, per_class_d = self.eval_val_fn(
            params_E, *self.val)
        poison_loss_d, poison_acc_d, _ = self.eval_pval_fn(
            params_E, *self.pval)
        vals.update(val_loss=val_loss_d, val_acc=val_acc_d,
                    base_acc=per_class_d[:, self.rep.base_class],
                    poison_loss=poison_loss_d,
                    poison_acc=poison_acc_d,
                    train_loss=info["train_loss"])
        if "fault_voters" in info:
            vals.update({k: info[k] for k in FAULT_INFO_KEYS})
        if "churn_away" in info:
            vals["churn_away"] = info["churn_away"]
        vals.update({k: info[k] for k in info
                     if k.startswith("tel_") or k in self.hlth_boundary})
        if self._rep_pending:
            # per-pack-round (round_ids, client_ids, rep_agree, rep_norm)
            # stacks since the last boundary ride the same (async) fetch
            vals["rep_rows"] = self._rep_pending
            self._rep_pending = []
        if self.drain is not None:
            self.drain.submit(self._emit_all, vals, rnd, rounds_done,
                              elapsed)
            return {}
        vals = jax.device_get(vals)  # static: ok(host-sync)
        return self._emit_all(vals, rnd, rounds_done, elapsed)

    # ----------------------------------------------------------- emit ---

    def _emit_all(self, vals, pack_rnd: int, rounds_done_now: int,
                  elapsed: float) -> Dict[int, BaseException]:
        """One eval boundary's per-tenant fan-out — runs on the drain
        thread (async) or inline (sync/scheduler); mirrors the solo
        train._emit_eval_body row order so tenant streams byte-compare
        to solo runs modulo wall-clock rows."""
        lane_on = "hlth_nonfinite" in vals
        if not lane_on:
            # --health off keeps the historical pack-level endpoint
            finite_warn(vals["finite"], where=f"pack round {pack_rnd}")
        now = time.perf_counter()
        # popped ONCE so an evict/retry pass cannot double-fold the
        # per-tenant ledgers (the solo _emit_eval_body discipline)
        rep_rows = vals.pop("rep_rows", None)
        errors: Dict[int, BaseException] = {}
        for e, slot in enumerate(self.slots):
            if not slot.active:
                continue
            try:
                self._emit_slot(e, slot, vals, pack_rnd, rounds_done_now,
                                elapsed, now, lane_on, rep_rows)
            except Exception as err:
                if not self.evict_on_anomaly:
                    raise
                errors[e] = err
        if self.t_steady is None:
            self.t_steady = now
            self.r_steady = rounds_done_now
        else:
            self.t_steady_end = now
            self.r_steady_end = rounds_done_now
        return errors

    def _emit_slot(self, e: int, slot: _Slot, vals, pack_rnd: int,
                   rounds_done_now: int, elapsed: float, now: float,
                   lane_on: bool, rep_rows=None) -> None:
        writer, cfg = slot.writer, slot.cfg
        ernd = pack_rnd + slot.offset  # the slot's own round index
        report = None
        if lane_on:
            # per-tenant health lane: the solo twin's assess/emit/
            # enforce (train._emit_eval_body) sliced per tenant —
            # Health/* rows land BEFORE Validation/*, the solo row
            # order, so tenant streams keep byte-parity with solo
            # runs. Each tenant is judged on ITS OWN committed-params
            # bit, not the pack-wide one (one diverging tenant must
            # not flag its pack-mates).
            hvals = {"finite":
                     float(vals["hlth_params_finite"][e]) >= 1.0,
                     "train_loss": float(vals["train_loss"][e])}
            for k in health_sentinel.boundary_keys(cfg):
                if k in vals:
                    hvals[k] = float(vals[k][e])
            report = health_monitor.assess(cfg, slot.health_ema, hvals)
            health_monitor.emit_rows(writer, report, ernd)
            health_monitor.enforce(
                cfg, report, where=f"pack round {ernd} tenant {e}")
        val_loss = float(vals["val_loss"][e])
        val_acc = float(vals["val_acc"][e])
        poison_loss = float(vals["poison_loss"][e])
        poison_acc = float(vals["poison_acc"][e])
        slot.cum_poison += poison_acc
        writer.scalar("Validation/Loss", val_loss, ernd)
        writer.scalar("Validation/Accuracy", val_acc, ernd)
        writer.scalar("Poison/Base_Class_Accuracy",
                      float(vals["base_acc"][e]), ernd)
        writer.scalar("Poison/Poison_Accuracy", poison_acc, ernd)
        writer.scalar("Poison/Poison_Loss", poison_loss, ernd)
        writer.scalar("Poison/Cumulative_Poison_Accuracy_Mean",
                      slot.cum_poison / ernd, ernd)
        writer.scalar("Train/Loss", float(vals["train_loss"][e]), ernd)
        if "fault_voters" in vals:
            writer.scalar("Faults/Dropped",
                          float(vals["fault_dropped"][e]), ernd)
            writer.scalar("Faults/Straggled",
                          float(vals["fault_straggled"][e]), ernd)
            writer.scalar("Faults/Effective_Voters",
                          float(vals["fault_voters"][e]), ernd)
        if "churn_away" in vals:
            writer.scalar("Churn/Sampled_Away",
                          float(vals["churn_away"][e]), ernd)
        tel = obs_telemetry.tenant_rows(vals, e, allowed=slot.tel_allowed)
        obs_telemetry.emit_scalars(writer, tel, ernd)
        rep_pred = ((lambda cid: cid < cfg.num_corrupt)
                    if cfg.num_corrupt > 0 else None)
        if slot.rep_tracker is not None and rep_rows:
            # the tenant's slice of the pack's [.., E, m] agreement rows
            # folds into ITS ledger on ITS clock (ernd = pack + offset),
            # mirroring the solo fold order; rows land after Defense/*
            # and before Throughput/*, the solo row order
            tracker = slot.rep_tracker
            for rnds, ids_blk, agrees, norms in rep_rows:
                ids_blk, agrees = np.asarray(ids_blk), np.asarray(agrees)
                norms = np.asarray(norms)
                if agrees.ndim == 2:             # single round [E, m]
                    tracker.fold(rnds[0] + slot.offset, ids_blk[e],
                                 agrees[e], norms[e])
                else:                            # chained [chain, E, m]
                    for j, r in enumerate(rnds):
                        tracker.fold(r + slot.offset, ids_blk[j, e],
                                     agrees[j, e], norms[j, e])
            obs_reputation.emit_rows(writer, tracker, ernd, rep_pred)
            for ev in tracker.drain_events():
                obs_events.emit(obs_reputation.SUSPECT_EVENT,
                                severity="warn", tenant=e, **ev)
        writer.scalar("Throughput/Rounds_Per_Sec",
                      rounds_done_now / elapsed, ernd)
        if (self.t_steady is not None
                and rounds_done_now > self.r_steady):
            writer.scalar("Throughput/Steady_Rounds_Per_Sec",
                          (rounds_done_now - self.r_steady)
                          / (now - self.t_steady), ernd)
        summary = {
            "round": ernd, "val_loss": val_loss, "val_acc": val_acc,
            "poison_loss": poison_loss, "poison_acc": poison_acc,
            "rounds_per_sec": rounds_done_now / elapsed}
        if tel:
            summary["defense"] = obs_telemetry.host_summary(tel)
        if slot.rep_tracker is not None:
            # the suspicion verdict as data: the same per-cell summary
            # key the solo path records (train.py _emit_eval_body), so
            # queue/sweep rows stay structurally identical packed or
            # serial (service/queue.SUMMARY_KEYS "suspicion")
            rep_sum = slot.rep_tracker.summary(rep_pred)
            summary["suspicion"] = rep_sum
            if "defense" in summary:
                summary["defense"]["rep_suspects"] = float(
                    rep_sum["suspect_count"])
                if "auc" in rep_sum:
                    summary["defense"]["rep_auc"] = float(rep_sum["auc"])
        if report is not None and report["rows"]:
            # the lane's verdict as data: queue rows
            # (service/queue.SUMMARY_KEYS) record per-cell health —
            # the SAME schema as the solo path's summary (train.py
            # _emit_eval_body), so packed-vs-serial rows stay
            # structurally identical
            summary["health"] = {k: float(v)
                                 for k, v in report["rows"].items()}
            # EMA commits LAST (the solo twin's discipline)
            slot.health_ema = report["new_state"]
        slot.summary = summary
        writer.flush()

    # -------------------------------------------------------- close ---

    def steady_rps(self) -> Optional[float]:
        if (self.t_steady is not None and self.t_steady_end is not None
                and self.r_steady_end > self.r_steady):
            return ((self.r_steady_end - self.r_steady)
                    / max(self.t_steady_end - self.t_steady, 1e-9))
        return None

    def close(self, loop_ok: bool = True) -> None:
        if self.drain is not None:
            if loop_ok:
                self.drain.flush()
            self.drain.close(raise_errors=False)
        if self._exec is not None:
            self._exec.shutdown(wait=False, cancel_futures=True)
        if not loop_ok:
            # a failed pack still flushes+releases every tenant's
            # metrics handle (the queue records the failure and moves
            # on; the success path closes writers via finalize_slot —
            # close() is not re-entrant)
            for slot in self.slots:
                if slot.active:
                    try:
                        slot.writer.close()
                    except Exception:
                        pass


def run_pack(cfgs, names: Optional[List[str]] = None
             ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Run E shape-compatible cell configs as ONE tenant pack, FIFO
    (every tenant starts and finishes together, offsets 0 — the PR-13
    semantics).

    Returns (per-tenant summary dicts in cell order, pack_info) where
    each summary matches the solo run-summary keys the queue consumes
    (service/queue.SUMMARY_KEYS) and pack_info carries the pack-level
    timing split (compile/AOT-acquisition vs steady seconds)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
        dispatch_schedule)
    engine = PackEngine(cfgs, names)
    E, rounds, snap = engine.width, engine.rounds, engine.snap
    print(f"[tenancy] pack of {E} tenants x {rounds} rounds "
          f"({', '.join(s.name for s in engine.slots)})")
    rounds_done = 0
    loop_ok = False
    t_loop = time.perf_counter()
    try:
        for unit in dispatch_schedule(0, rounds, snap, engine.chain_n,
                                      False,
                                      engine.chained_fn is not None):
            rnd, info = engine.dispatch_unit(unit)
            rounds_done += len(unit)
            if rnd % snap == 0:
                engine.eval_boundary(rnd, info, rounds_done,
                                     time.perf_counter() - t_loop)
        loop_ok = True
    finally:
        engine.close(loop_ok)

    elapsed = time.perf_counter() - t_loop
    wall = time.perf_counter() - engine.t0
    pack_rps = rounds_done / max(elapsed, 1e-9)
    steady_rps = engine.steady_rps()
    summaries = []
    for e in range(E):
        summary = engine.finalize_slot(e)
        summary["rounds_per_sec"] = pack_rps
        if steady_rps is not None:
            summary["steady_rounds_per_sec"] = steady_rps
        summaries.append(summary)
    pack_info = {"tenants": E, "rounds": rounds,
                 "wall_s": round(wall, 3),
                 "compile_s": round(engine.compile_s, 3),
                 "rounds_per_sec": round(pack_rps, 4)}
    if steady_rps is not None:
        pack_info["steady_rounds_per_sec"] = round(steady_rps, 4)
    print(f"[tenancy] pack done: {E} tenants x {rounds} rounds in "
          f"{wall:.1f}s ({pack_rps:.2f} pack-rounds/sec)")
    return summaries, pack_info
