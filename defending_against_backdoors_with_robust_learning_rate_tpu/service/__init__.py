"""Continuous-service subsystem: the one-shot trainer as a long-running,
supervised FL service.

    churn.py       seeded arrive/depart/rejoin client lifecycles — the
                   cohort process as a simulator primitive (FedJAX,
                   arXiv:2108.02117), feeding the existing
                   participation-mask protocol with zero extra collectives
    supervisor.py  deadline + exponential-backoff retry around every
                   dispatch/eval/checkpoint unit, with failure
                   classification (transient / wedged / poisoned) and
                   graceful degradation
    chaos.py       deterministic fault injector (kill-mid-round,
                   wedge-dispatch, wedge-drain, corrupt-checkpoint,
                   slow-eval) the recovery tests and the CI chaos drill
                   drive
    driver.py      the service loop: rounds stream under churn, units run
                   supervised, checkpoints are journaled for crash-exact
                   resume (utils/checkpoint.py)
    queue.py       experiment queue: scenario cells back-to-back in one
                   process against one AOT bank (FL_PyTorch's
                   simulator-as-service gap, arXiv:2202.03099)
    tenancy.py     multi-tenant tenant packs (ISSUE 13): up to E
                   shape-compatible queue cells as ONE resident *_mt
                   program (fl/tenancy.py), grouped by the
                   compile-cache fingerprint's field algebra, metrics
                   fanned back out per tenant through the MetricsDrain
"""
