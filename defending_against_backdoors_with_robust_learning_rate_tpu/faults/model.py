"""Seeded per-round fault sampling + payload validation.

Fault draws are pure functions of a key derived from the round key
(`fault_key`), so they are reproducible under ``--seed``, identical across
the single-device and sharded paths (every device of a mesh derives the
same replicated [m] draw from the same replicated key — no collective
needed to agree on who failed), and identical between per-round and
chained dispatch. All outputs are fixed [m]-shaped arrays: varying fault
draws across rounds reuse one compiled round program.

Three failure modes (all off by default; any nonzero rate enables the
faults path, `Config.faults_enabled`):

- dropout (``--dropout_rate``): Bernoulli per sampled agent; a dropped
  agent's update never reaches aggregation (participation mask). At least
  one participant is always retained — a fully-empty round has no defined
  aggregate.
- stragglers (``--straggler_rate``/``--straggler_epochs``): a straggler's
  local training is truncated to ``straggler_epochs`` epochs via the
  batch-weight machinery of fl/client.py (epochs past the budget become
  exact no-op steps); the partial update still participates.
- corrupt payloads (``--corrupt_rate``/``--corrupt_mode``): the agent's
  returned update is overwritten with garbage (NaN, or a huge finite
  constant). Server-side `payload_valid` rejects non-finite payloads (and
  optionally payloads over ``--payload_norm_cap``) before they can enter
  the mask — under ``--debug_nan`` the injected NaNs instead trip the
  checkify guards, which is the supported way to exercise them.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree

# fold_in tag separating the fault stream from every other per-round stream
# (the driver derives k_sample/k_train/k_noise by split; folding a constant
# into k_noise leaves all existing streams untouched, so a zero-rate faults
# config reproduces the dense path bit-for-bit)
FAULTS_KEY_TAG = 0x5FA17


class FaultDraw(NamedTuple):
    participate: jax.Array   # [m] bool — survived dropout
    straggler: jax.Array     # [m] bool — epoch-truncated this round
    ep_budget: jax.Array     # [m] int32 — local epochs each agent completes
    corrupt: jax.Array       # [m] bool — payload replaced with garbage


def fault_key(k_noise):
    """The round's fault stream, derived without consuming k_noise."""
    return jax.random.fold_in(k_noise, FAULTS_KEY_TAG)


def sample_faults(cfg, key, m: int, corrupt_flags=None) -> FaultDraw:
    """One round's fault draw for the m sampled agents.

    `corrupt_flags` ([m] bool, slot holds a malicious agent) feeds the
    ``--faults_spare_corrupt`` adversarial participation model: attackers
    never drop out while honest voters churn — the regime where the RLR
    defense's effective majority is thinnest."""
    k_drop, k_strag, k_corr = jax.random.split(key, 3)
    u = jax.random.uniform(k_drop, (m,))
    drop = u < cfg.dropout_rate
    if cfg.faults_spare_corrupt and corrupt_flags is not None:
        drop = drop & ~corrupt_flags
    # never lose the whole round: if every agent dropped, retain the one
    # whose draw was farthest from the dropout region
    keep = jnp.argmax(u)
    drop = jnp.where(jnp.all(drop) & (jnp.arange(m) == keep), False, drop)
    straggler = jax.random.uniform(k_strag, (m,)) < cfg.straggler_rate
    ep_budget = jnp.where(
        straggler, min(cfg.straggler_epochs, cfg.local_ep),
        cfg.local_ep).astype(jnp.int32)
    corrupt = jax.random.uniform(k_corr, (m,)) < cfg.corrupt_rate
    return FaultDraw(~drop, straggler, ep_budget, corrupt)


# a large-but-finite f32 payload: slips past the finite check (that is the
# point — it exercises the norm-cap / robust-aggregation layers instead)
HUGE_PAYLOAD = 1e30


def inject_corrupt(stacked_updates, corrupt, mode: str):
    """Overwrite corrupt agents' rows with garbage. Deterministic constants
    (NaN / ±HUGE via the row's update sign would add RNG for no modelling
    value), so the vmap and shard_map paths agree bit-for-bit."""
    if mode == "nan":
        val = jnp.nan
    elif mode == "huge":
        val = HUGE_PAYLOAD
    else:
        raise ValueError(f"corrupt_mode must be nan|huge, got {mode!r}")

    def leaf(u):
        mask = corrupt.reshape((-1,) + (1,) * (u.ndim - 1))
        return jnp.where(mask, jnp.full((), val, u.dtype), u)
    return tree.map(leaf, stacked_updates)


def payload_valid(stacked_updates, norm_cap: float = 0.0):
    """[m] bool server-side payload validation: every coordinate finite,
    and (when ``norm_cap`` > 0) global L2 norm under the cap. A huge-but-
    finite payload overflows its squared norm to +inf, which the cap
    comparison rejects as well."""
    leaves = jax.tree_util.tree_leaves(stacked_updates)
    m = leaves[0].shape[0]
    valid = jnp.ones((m,), bool)
    sumsq = jnp.zeros((m,), jnp.float32)
    for u in leaves:
        flat = u.reshape(m, -1)
        valid = valid & jnp.isfinite(flat).all(axis=1)
        if norm_cap > 0:
            sumsq = sumsq + jnp.sum(
                flat.astype(jnp.float32) * flat.astype(jnp.float32), axis=1)
    if norm_cap > 0:
        valid = valid & (sumsq <= jnp.float32(norm_cap) ** 2)
    return valid


def fault_scalars(draw: FaultDraw, mask):
    """Degradation observability: the Faults/* scalar set the driver logs
    (fault_dropped excludes payload-validation kills — those show up as the
    gap between m - dropped and effective voters)."""
    return {
        "fault_dropped": jnp.sum((~draw.participate).astype(jnp.float32)),
        "fault_straggled": jnp.sum(draw.straggler.astype(jnp.float32)),
        "fault_voters": jnp.sum(mask.astype(jnp.float32)),
    }
