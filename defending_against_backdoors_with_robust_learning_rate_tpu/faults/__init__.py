"""Fault injection & elastic participation.

The reference simulator (and the seed of this repo) assumes every sampled
agent returns a complete, on-time, well-formed update every round. At
production scale that is the exception: clients drop out mid-round, straggle
(return after training fewer local epochs), or return corrupt payloads.
This package makes those failure modes first-class *inside the jitted
round* — fault draws are seeded per-round functions of the round key, all
shapes stay static, and one compiled program serves every round regardless
of which agents fail:

    model.py    seeded per-round fault sampling (Bernoulli dropout,
                straggler epoch truncation, corrupt-payload injection) and
                server-side payload validation
    masking.py  the participation-mask protocol: masked weighted sums,
                masked sign votes with a mask-aware RLR threshold, masked
                median/sort via +inf sentinel padding — every aggregation
                rule operates on a fixed [m]-shaped mask

Dropout changes the effective voter count of the paper's RLR
sign-agreement defense, so this subsystem opens the experiment axis the
seed could not study: how robust is the defense when the honest-voter
majority is thinned by churn while attackers never drop out
(``--faults_spare_corrupt``)?
"""
