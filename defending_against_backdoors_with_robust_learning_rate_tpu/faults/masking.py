"""The participation-mask protocol: aggregation over a fixed [m]-shaped mask.

Every aggregation rule in `ops/aggregate.py` (and its collective twin in
`parallel/rounds.py`) can run over a traced boolean mask marking which of
the m sampled agents actually delivered a usable update this round. Masked
agents are excluded *arithmetically*, never by shrinking arrays, so shapes
stay static and one compiled round program serves every fault draw:

- sum-based rules (avg, sign, RLR vote, RFA weights): non-participant rows
  and their weights are zeroed (`jnp.where` on the row, which also
  sanitizes NaN/garbage payloads — a multiply by 0 would propagate NaN);
- sort-based rules (comed, trmean): non-participant rows become +inf
  sentinels that sort to the end; the median/trim indices are traced
  functions of the effective count;
- krum: non-participant rows/columns of the pairwise-distance matrix are
  +inf, the neighbour count k follows the effective count, and masked
  candidates can never win the argmin.

Bit-parity contract (tests/test_faults.py): with an all-ones mask every
helper is bit-identical to the dense path in ops/aggregate.py, because each
masked formulation degenerates to the same op sequence: `where(True, x, s)
== x` bitwise; every reduction keeps the dense path's SHAPE (full-[m] sums
where the dense rule sums all rows, traced-start/static-size dynamic-slice
windows where the dense rule sums a slice — reduction shape determines
XLA's add association, so a shape mismatch drifts by an ulp); and traced
counts divide via reciprocal-multiply exactly like XLA's strength-reduced
divide-by-constant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.aggregate import (
    RFA_EPS, RFA_ITERS, agent_sq_dists, sq_dist_accum)


def _bcast(mask, u):
    """[m] mask broadcast against an [m, ...] row-stacked array."""
    return mask.reshape((-1,) + (1,) * (u.ndim - 1))


def count(mask):
    """Effective participant count as int32 (traced)."""
    return jnp.sum(mask.astype(jnp.int32))


def count_f32(mask):
    return jnp.sum(mask.astype(jnp.float32))


def zero_rows(u, mask):
    """Rows of non-participants replaced by exact zeros. `where` (not a
    multiply) so NaN/inf garbage in masked rows cannot propagate."""
    return jnp.where(_bcast(mask, u), u, jnp.zeros((), u.dtype))


def zero_masked(stacked_updates, mask):
    """`zero_rows` over every leaf of a stacked update pytree."""
    return tree.map(lambda u: zero_rows(u, mask), stacked_updates)


def guard_empty(agg_tree, mask):
    """All-invalid round: every sampled agent dropped or failed payload
    validation (the dropout sampler guarantees one *survivor*, but its
    payload can still be rejected). The aggregate is then undefined (0/0
    weighted sums, 1/0 Weiszfeld scales, sentinel medians) — replace it
    with zeros so the round is a parameter-preserving no-op instead of NaN
    poisoning every subsequent round. Faults/Effective_Voters logs 0 for
    the round, so the event is observable."""
    any_valid = jnp.any(mask)
    return tree.map(lambda a: jnp.where(any_valid, a, jnp.zeros_like(a)),
                    agg_tree)


def rlr_threshold(cfg, mask, base=None):
    """Mask-aware RLR vote threshold. ``abs`` keeps the paper's absolute
    count (the vote just loses the masked voters); ``scaled`` shrinks the
    threshold with the effective electorate (threshold * n_eff / m) so the
    required agreement *fraction* is invariant under churn. ``base``
    overrides the config constant with a traced scalar — the multi-tenant
    pack's per-tenant threshold knob (fl/tenancy.py); None keeps the solo
    paths' Python float."""
    thr = float(cfg.robustLR_threshold) if base is None else base
    if cfg.rlr_threshold_mode == "scaled":
        return thr * count_f32(mask) / mask.shape[0]
    return thr


# ------------------------------------------------------------ array level ---

def median_rows(u, mask, n_eff):
    """Lower median over participant rows of [m, ...]: +inf sentinels sort
    masked rows last; the torch-style lower-median index follows the traced
    effective count."""
    srt = jnp.sort(jnp.where(_bcast(mask, u), u, jnp.inf), axis=0)
    return jnp.take(srt, (n_eff - 1) // 2, axis=0)


def trimmed_mean_rows(u, mask, n_eff, trim_k):
    """Coordinate-wise trimmed mean over participant rows of [m, ...]: sort
    with +inf sentinels, then average the untrimmed band [k, n_eff - k).

    Bit-parity construction: the band is read through a `dynamic_slice`
    window of the DENSE band's static length (traced start k, so the
    reduction has the exact shape of the dense slice sum — a full-[m]
    masked sum would associate its adds differently and drift by an ulp),
    with a within-window position mask zeroing the traced tail. The final
    scale is a reciprocal-multiply, not a division: XLA strength-reduces
    the dense path's divide-by-constant count to a multiply, so the
    traced-count path must take the same rounding."""
    m = u.shape[0]
    srt = jnp.sort(jnp.where(_bcast(mask, u), u, jnp.inf), axis=0)
    k_s = max(0, min(int(trim_k), (m - 1) // 2))   # dense static clamp
    L = m - 2 * k_s                                # dense band length
    k = jnp.clip(trim_k, 0, (n_eff - 1) // 2)      # traced effective trim
    win = jax.lax.dynamic_slice_in_dim(srt, k, L, axis=0)
    pos = jnp.arange(L).reshape((-1,) + (1,) * (u.ndim - 1))
    # cnt can only exceed L in the pathological maximal-trim shapes
    # (m <= 2*trim_k + 2); clamp so the mean stays a mean
    cnt = jnp.minimum(n_eff - 2 * k, L)
    band = pos < cnt
    return (jnp.sum(jnp.where(band, win, jnp.zeros((), win.dtype)), axis=0)
            * (1.0 / cnt.astype(jnp.float32)))


def krum_best(dist, mask, n_eff, num_corrupt):
    """Masked Krum winner over a clamped [m, m] squared-distance matrix:
    rows/columns of non-participants are +inf, the neighbour count follows
    the effective electorate (clipped so selected positions only ever cover
    finite distances), and masked candidates score +inf so the argmin is
    always a participant."""
    m = dist.shape[0]
    pair = mask[:, None] & mask[None, :]
    dist = jnp.where(pair, dist, jnp.inf)
    srt = jnp.sort(dist, axis=1)
    # dense k = max(m - f - 2, 1); masked follows n_eff, with the upper clip
    # keeping selected positions inside the n_eff finite entries of a valid
    # row (and k = 0 when a single survivor has no neighbours to score).
    # The window over positions 1..L is the dense slice — static shape, so
    # the score reduction associates exactly like the dense path's
    # (trimmed_mean_rows explains the parity construction).
    L = max(m - num_corrupt - 2, 1)
    k = jnp.clip(n_eff - num_corrupt - 2,
                 jnp.minimum(n_eff - 1, 1), jnp.maximum(n_eff - 1, 0))
    win = srt[:, 1:L + 1]
    sel = jnp.arange(L)[None, :] < k
    scores = jnp.sum(jnp.where(sel, win, jnp.zeros((), win.dtype)), axis=1)
    return jnp.argmin(jnp.where(mask, scores, jnp.inf))


# ------------------------------------------------------------- tree level ---

def masked_avg(stacked_updates, data_sizes, mask):
    """Weighted FedAvg over participants (agg_avg semantics, masked)."""
    w = jnp.where(mask, data_sizes.astype(jnp.float32), 0.0)
    total = jnp.sum(w)
    zeroed = zero_masked(stacked_updates, mask)

    def leaf(u):
        wshape = (-1,) + (1,) * (u.ndim - 1)
        return jnp.sum(u * w.reshape(wshape), axis=0) / total
    return tree.map(leaf, zeroed)


def masked_sign(stacked_updates, mask):
    """Majority-sign over participants: zeroed rows vote sign(0) = 0."""
    zeroed = zero_masked(stacked_updates, mask)
    return tree.map(lambda u: jnp.sign(jnp.sum(jnp.sign(u), axis=0)), zeroed)


def masked_comed(stacked_updates, mask):
    n_eff = count(mask)
    return tree.map(lambda u: median_rows(u, mask, n_eff), stacked_updates)


def masked_trmean(stacked_updates, mask, trim_k):
    n_eff = count(mask)
    return tree.map(lambda u: trimmed_mean_rows(u, mask, n_eff, trim_k),
                    stacked_updates)


def masked_krum(stacked_updates, mask, num_corrupt):
    """Krum over participants. Distances accumulate over zeroed rows (so
    garbage payloads cannot poison the matrix); masked candidates are
    disqualified inside `krum_best`. The winner's update is read from the
    zeroed stack — identical to its raw update for any participant."""
    zeroed = zero_masked(stacked_updates, mask)
    leaves = jax.tree_util.tree_leaves(zeroed)
    m = leaves[0].shape[0]
    d = jnp.zeros((m, m), jnp.float32)
    for u in leaves:
        d = sq_dist_accum(d, u.reshape(m, -1))
    d = jnp.maximum(d, 0.0)
    best = krum_best(d, mask, count(mask), num_corrupt)
    return tree.map(lambda u: u[best], zeroed)


def masked_rfa(stacked_updates, mask, iters: int = RFA_ITERS,
               eps: float = RFA_EPS):
    """Smoothed-Weiszfeld geometric median over participants (agg_rfa
    semantics): the iterate starts from the participant mean and masked
    agents carry weight 0 in every reweighting."""
    zeroed = zero_masked(stacked_updates, mask)
    n_eff = count_f32(mask)
    mf = mask.astype(jnp.float32)
    # reciprocal-multiply: the dense mean's divide-by-constant is
    # strength-reduced by XLA (see trimmed_mean_rows)
    v = tree.map(
        lambda u: jnp.sum(u.astype(jnp.float32), axis=0) * (1.0 / n_eff),
        zeroed)
    for _ in range(iters):
        w = mf / jnp.maximum(jnp.sqrt(agent_sq_dists(zeroed, v)), eps)
        wsum = jnp.sum(w)

        def leaf(u, w=w, wsum=wsum):
            wshape = (-1,) + (1,) * (u.ndim - 1)
            return jnp.sum(u * w.reshape(wshape), axis=0) / wsum
        v = tree.map(leaf, zeroed)
    return v


def masked_aggregate(stacked_updates, data_sizes, cfg, mask):
    """Mask-aware dispatch mirroring ops/aggregate.aggregate_updates (the
    caller adds server noise; noise is mask-independent)."""
    if cfg.aggr == "avg":
        return masked_avg(stacked_updates, data_sizes, mask)
    if cfg.aggr == "comed":
        return masked_comed(stacked_updates, mask)
    if cfg.aggr == "sign":
        return masked_sign(stacked_updates, mask)
    if cfg.aggr == "trmean":
        return masked_trmean(stacked_updates, mask, cfg.num_corrupt)
    if cfg.aggr == "krum":
        return masked_krum(stacked_updates, mask, cfg.num_corrupt)
    if cfg.aggr == "rfa":
        return masked_rfa(stacked_updates, mask)
    raise ValueError(f"unknown aggr {cfg.aggr!r}")
