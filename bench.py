#!/usr/bin/env python
"""Headline benchmark: FL rounds/sec on the flagship config.

Config = BASELINE.json configs[1]: fmnist-shaped data, 10 agents, 1 corrupt,
poison_frac=0.5, robustLR_threshold=4, local_ep=2, bs=256 (the paper's
FMNIST attack+defense setting, src/runner.sh:18). Real FMNIST is used when
present under ./data; otherwise the deterministic synthetic fallback with the
same 60k x 28x28 geometry.

Prints ONE JSON line:
  {"metric": "fl_rounds_per_sec", "value": N, "unit": "rounds/sec",
   "vs_baseline": N, ...} (vs_baseline only for the default fmnist config —
the resnet9 config has no reference counterpart to compare against)

value is STEADY-STATE rounds/sec (post-compile); `compile_s` records the
first-block compile separately (VERDICT r1 #9). Compile persistence
(utils/compile_cache.py) splits that further: `cache_hit` says whether the
round-block executable was loaded from the serialized-executable bank,
`compile_s_cold` is the full trace+lower+XLA cost (from this run, or from
the banking run's manifest on a hit) and `compile_s_warm` the deserialize
cost of a warm start; `host_sync` records the per-eval-boundary blocking
host sync the driver's async metrics drain removes. vs_baseline is the speedup
over the reference-semantics torch loop measured on this host
(BASELINE_MEASURED.json, scripts/measure_reference_baseline.py): the
reference trains sampled agents sequentially (src/federated.py:68-72), so
its round time is agents * local_ep * batches * sec_per_batch_step.

Wedge-safety (VERDICT r1 #2): the TPU backend behind this machine's tunnel
can hang indefinitely (even `jax.devices()`) after a killed process. The
backend is therefore probed in a BOUNDED SUBPROCESS first; on probe failure
the benchmark falls back to CPU and says so in the JSON (`device`,
`backend_note`) instead of hanging or stack-tracing into the driver's
capture. The main process itself never wraps TPU work in a watchdog that
could kill mid-compile — that is what wedges the chip.
"""

import argparse
import json
import os
import subprocess
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


PROBE_CODE = "import jax; print('BACKEND=' + jax.default_backend())"


def probe_backend(timeout_s: float, retries: int = 3,
                  retry_wait_s: float = 45.0,
                  code: str = PROBE_CODE) -> str | None:
    """Return the default backend name, probed in a bounded subprocess.

    None means the backend never came up within the budget (wedged tunnel /
    missing hardware). Only the *probe* child is ever killed — it does no
    compilation, so killing it cannot wedge a healthy chip mid-compile.
    A wedge can clear between attempts, so a failed probe is retried a few
    times (total worst case: retries * (timeout_s + retry_wait_s), still
    bounded) before giving up. `code` is injectable so tests can drive the
    subprocess/timeout/retry machinery without a jax backend."""
    for attempt in range(retries):
        timed_out = False
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired:
            out, timed_out = None, True
        if out is not None and out.returncode == 0:
            for line in out.stdout.splitlines():
                if line.startswith("BACKEND="):
                    return line.split("=", 1)[1]
        why = ("timed out (wedged tunnel?)" if timed_out else
               f"rc={out.returncode}: {out.stderr.strip()[-300:]}")
        if attempt < retries - 1:
            # a hang can clear between attempts, so wait before re-probing;
            # a fast deterministic failure won't, so don't
            wait = retry_wait_s if timed_out else 0.0
            log(f"[bench] probe attempt {attempt + 1}/{retries} failed "
                f"({why}); retrying" + (f" in {wait:.0f}s" if wait else ""))
            time.sleep(wait)
        else:
            log(f"[bench] probe attempt {attempt + 1}/{retries} failed "
                f"({why})")
    return None


# peak dense-matmul throughput by device_kind substring (TFLOP/s, bf16);
# public chip specs — used to turn measured FLOP/s into an MFU figure.
# f32 inputs on the MXU run through the same bf16 pipeline under JAX's
# default matmul precision, so bf16 peak is the honest denominator either way
PEAK_BF16_TFLOPS = (
    ("v6", 918.0),        # v6e (Trillium)
    ("v5p", 459.0),
    ("v5", 197.0),        # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for key, val in PEAK_BF16_TFLOPS:
        if key in kind:
            return val
    return None


def bench_config(name: str, cpu_fallback: bool = False,
                 remat_policy: str = "block", agent_chunk: int = -1,
                 **extra):
    """The two benchmark configs, importable (scripts/precompile.py banks
    their program families offline from the very same construction).

    fmnist = the flagship paper config (BASELINE.json configs[1]);
    resnet9 = the north-star cifar10 ResNet-9 DBA+RLR config
    (BASELINE.json configs[3]: 40 agents, 4 corrupt, thr=8, remat +
    agent_chunk=10)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.config import (
        Config)
    if name == "resnet9":
        return Config(data="cifar10", num_agents=40, local_ep=2, bs=256,
                      num_corrupt=4, poison_frac=0.5, pattern_type="plus",
                      robustLR_threshold=8, arch="resnet9",
                      remat=(remat_policy != "none"),
                      remat_policy=("block" if remat_policy == "none"
                                    else remat_policy),
                      agent_chunk=(10 if agent_chunk < 0 else agent_chunk),
                      synth_train_size=(5000 if cpu_fallback else 50000),
                      synth_val_size=10000, seed=0, **extra)
    return Config(data="fmnist", num_agents=10, local_ep=2, bs=256,
                  num_corrupt=1, poison_frac=0.5, robustLR_threshold=4,
                  synth_train_size=(6000 if cpu_fallback else 60000),
                  synth_val_size=10000, seed=0, **extra)


def train_step_flops(model, params, norm, cfg, image_shape):
    """XLA's own FLOP count for ONE client fwd+bwd minibatch step (the
    compiler's cost analysis of the compiled program — no hand model).
    Multiplied out by the driver: agents x epochs x batches per round.

    Callers pass a NON-remat model instance: MFU is model-FLOPs utilization,
    so rematerialization's recompute work must not inflate the numerator
    (the timed program may still remat — that cost shows up in the wall
    clock, where it belongs)."""
    import jax
    import jax.numpy as jnp

    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
        masked_ce)

    x = jnp.zeros((cfg.bs,) + tuple(image_shape), jnp.float32)
    y = jnp.zeros((cfg.bs,), jnp.int32)
    w = jnp.ones((cfg.bs,), bool)

    def loss_fn(p):
        logits = model.apply({"params": p}, norm(x), train=True,
                             rngs={"dropout": jax.random.PRNGKey(0)})
        return masked_ce(logits, y, w)

    compiled = jax.jit(jax.value_and_grad(loss_fn)).lower(params).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float(ca.get("flops", 0.0))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default="",
                    help="force a jax platform (skips the probe)")
    ap.add_argument("--bench_config", choices=("fmnist", "resnet9"),
                    default="fmnist",
                    help="fmnist = flagship paper config (BASELINE.json "
                         "configs[1], the default the driver records); "
                         "resnet9 = the north-star cifar10 ResNet-9 DBA+RLR "
                         "config (BASELINE.json configs[3]: 40 agents, 4 "
                         "corrupt, thr=8, remat + agent_chunk=10)")
    ap.add_argument("--chain", type=int, default=10,
                    help="rounds fused per lax.scan block")
    ap.add_argument("--blocks", type=int, default=3,
                    help="timed steady-state blocks")
    ap.add_argument("--dtype", default="",
                    help="override compute dtype (f32|bf16)")
    ap.add_argument("--rng_impl", choices=("auto", "threefry", "rbg"),
                    default="auto",
                    help="PRNG bit generator (auto = hardware rbg on TPU)")
    ap.add_argument("--use_pallas", action="store_true",
                    help="fused Pallas RLR+FedAvg server step")
    ap.add_argument("--faults", action="store_true",
                    help="also measure rounds/sec at 30%% client dropout "
                         "(faults/ masking path) and report the masking "
                         "overhead vs the dense 0%% run")
    ap.add_argument("--health", choices=("on", "off", "both"),
                    default="on",
                    help="in-program health sentinel lane "
                         "(health/sentinel.py, default on — the shipped "
                         "config). 'off' re-points the headline at the "
                         "lane-free program; 'both' keeps the on "
                         "headline and ALSO measures the off twin "
                         "(health_ab in the output JSON — the ISSUE-14 "
                         "<=1%% overhead acceptance A/B)")
    ap.add_argument("--reputation", choices=("auto", "on", "off", "both"),
                    default="auto",
                    help="in-program reputation lanes (obs/reputation.py: "
                         "per-sampled-client rep_agree + rep_norm rows, "
                         "default auto = on whenever a sign vote exists "
                         "and the fused Pallas commit is not in use). "
                         "'off' re-points the headline at the lane-free "
                         "program; 'both' keeps the auto headline and "
                         "ALSO measures the off twin (reputation_ab in "
                         "the output JSON — the ISSUE-20 <1%% overhead "
                         "acceptance A/B)")
    ap.add_argument("--telemetry", choices=("off", "basic", "full"),
                    default="off",
                    help="also measure rounds/sec with in-jit defense "
                         "telemetry (obs/telemetry.py) at this level and "
                         "report the overhead vs the off run (the "
                         "headline value stays the off number)")
    ap.add_argument("--events", choices=("off", "both"), default="off",
                    help="'both' re-measures the headline blocks with a "
                         "live event ledger + Prometheus textfile "
                         "exporter updated at block cadence (the service "
                         "plane's boundary cadence upper bound) and "
                         "reports the overhead (events_ab in the output "
                         "JSON — the ISSUE-15 <1%% acceptance A/B)")
    ap.add_argument("--population_ladder", default="",
                    help="comma-separated client populations (e.g. "
                         "10000,100000,1000000): measure cohort-sampled "
                         "(data/bank.py + data/cohort.py) rounds/sec at "
                         "each rung with the flagship's cohort size, "
                         "recording host-RSS/HBM watermarks per rung — the "
                         "constant-memory evidence (ISSUE 7). Also runs "
                         "the equal-cohort dense-vs-cohort A/B on the "
                         "flagship config (label_shards bank: identical "
                         "shards, the delta is pure cohort machinery)")
    ap.add_argument("--ladder_partitioner",
                    choices=("dirichlet", "pathological"),
                    default="dirichlet",
                    help="client-bank partitioner for the ladder rungs "
                         "(label_shards cannot reach these populations)")
    ap.add_argument("--ladder_spc", type=int, default=0,
                    help="samples per client on the ladder rungs (0 = "
                         "auto clamp; the SAME value lands on every rung, "
                         "so rung rounds/sec are compute-comparable)")
    ap.add_argument("--train_layout", choices=("vmap", "megabatch", "both"),
                    default="",
                    help="A/B the local-training compute layout (ISSUE "
                         "10, fl/client.py): vmap = per-client batched "
                         "steps; megabatch = the client axis folded into "
                         "one [m*bs, ...] pass with client-segmented "
                         "loss/grad reductions. 'both' measures each "
                         "layout's steady rounds/sec + analytic-FLOP "
                         "MFU (train_layout_ab in the output JSON; the "
                         "headline value stays the vmap number); a "
                         "single value re-runs the headline under that "
                         "layout")
    ap.add_argument("--agg_layout", choices=("leaf", "bucket", "both"),
                    default="",
                    help="A/B the sharded aggregation collective shape "
                         "(ISSUE 8, parallel/buckets.py): measure "
                         "rounds/sec of the shard_map round program under "
                         "the per-leaf psum plan and/or the bucketed "
                         "reduce-scatter plan on the local mesh, with "
                         "jaxpr + compiled-HLO collective counts per "
                         "layout in the output JSON (agg_layout_ab)")
    ap.add_argument("--agg_mode", choices=("sync", "buffered", "both"),
                    default="sync",
                    help="aggregation mode (ISSUE 12, fl/buffered.py): "
                         "buffered runs the headline through the "
                         "buffered-async tick program; both ALSO "
                         "measures an A/B — buffered at K=m (the pure "
                         "mode overhead, acceptance <=3%%) plus sync "
                         "rounds/sec vs buffered ticks/sec at 30%%/50%% "
                         "straggler rates (agg_mode_ab in the output "
                         "JSON; BENCH_NOTES r13)")
    ap.add_argument("--tenants", type=int, default=0,
                    help=">=2: tenancy A/B (ISSUE 13, tenancy_ab in the "
                         "output JSON): an equal 16-cell shape-compatible "
                         "cell list through the serial experiment queue "
                         "vs the tenant-packed queue at this pack width — "
                         "cells/hour per arm + the packed/serial speedup "
                         "(service/tenancy.py)")
    ap.add_argument("--status_file", default="logs/status.json",
                    help="heartbeat path (obs/heartbeat.py) the session "
                         "stall detector reads; empty disables")
    ap.add_argument("--profile_rounds", type=int, default=0,
                    help=">0: after the timed steady blocks, capture a "
                         "jax.profiler window of (at least) this many "
                         "extra rounds and attribute device time "
                         "(obs/attribution.py: compute/collective/gap + "
                         "named-scope split as `attribution` in the "
                         "output JSON; the timed figure is unaffected)")
    ap.add_argument("--profile_trace_dir", default="logs/bench_profile",
                    help="where the --profile_rounds capture lands "
                         "(re-parse offline via scripts/trace_top_ops.py "
                         "--parse or python -m ...obs.report)")
    ap.add_argument("--remat_policy", choices=("block", "conv", "none"),
                    default="block",
                    help="resnet9 config only: block = full blockwise "
                         "remat (r4 baseline, +33%% fwd recompute), conv = "
                         "selective save-conv-outputs remat, none = no "
                         "remat at all (viable at bf16 with agent_chunk)")
    ap.add_argument("--agent_chunk", type=int, default=-1,
                    help="resnet9 config only: override the agent chunk "
                         "size (-1 keeps the config default of 10; 0 = "
                         "full 40-agent vmap)")
    ap.add_argument("--synth_train_size", type=int, default=0,
                    help="override the synthetic dataset size (forces the "
                         "synthetic generator; for CI verification of the "
                         "warm-start path on small shapes; 0 = config "
                         "default). The emitted value is NOT comparable "
                         "to full-shape rows (synth_override in the JSON)")
    ap.add_argument("--no_compile_cache", action="store_true",
                    help="disable the persistent XLA cache and the "
                         "serialized-executable AOT bank "
                         "(utils/compile_cache.py); every run compiles cold")
    ap.add_argument("--compile_cache_dir", default="",
                    help="compile-cache root (default: "
                         "$RLR_COMPILE_CACHE_DIR or ~/.cache/rlr_fl)")
    ap.add_argument("--probe_timeout", type=float, default=90.0)
    args = ap.parse_args()

    # advisor r5 (bench.py:160): these knobs only exist on the resnet9
    # config — flag the silent no-op instead of swallowing it, and record
    # it in the output JSON so a sweep row can't be misread as an A/B
    ignored_flags = []
    if args.bench_config != "resnet9":
        if args.remat_policy != "block":
            ignored_flags.append("--remat_policy")
        if args.agent_chunk != -1:
            ignored_flags.append("--agent_chunk")
    if ignored_flags:
        log(f"[bench] WARNING: {', '.join(ignored_flags)} only apply to "
            f"--bench_config resnet9 and are IGNORED for "
            f"{args.bench_config!r} (recorded as ignored_flags in the "
            f"output JSON)")

    # observability (obs/): span-trace the bench phases and heartbeat the
    # session stall detector through them (status.json replaces the old
    # stderr-growth liveness heuristic; compile_in_flight marks the window
    # a watchdog must never kill into)
    from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
        Heartbeat, SpanTracer)
    hb = Heartbeat(args.status_file, enabled=bool(args.status_file))
    tracer = SpanTracer(on_end=hb.span_hook)
    hb.update(phase="probe", force=True)

    import jax

    backend_note = ""
    cpu_fallback = False
    if args.platform:
        # explicit platform: honor the requested shapes as-is
        jax.config.update("jax_platforms", args.platform)
    else:
        with tracer.span("bench/probe"):
            probed = probe_backend(args.probe_timeout)
        if probed is None:
            backend_note = (f"default backend unreachable within "
                            f"{args.probe_timeout:.0f}s (wedged TPU "
                            f"tunnel?); CPU fallback on reduced shapes")
            log(f"[bench] WARNING: {backend_note}")
            jax.config.update("jax_platforms", "cpu")
            cpu_fallback = True
        else:
            log(f"[bench] probed backend: {probed}")
    if cpu_fallback:
        # this host has very few cores; the full 60k config would run for
        # an hour — shrink the dataset (same agent/epoch/batch structure)
        # so the fallback still emits a number in a few minutes. chain=1:
        # the chained rounds-scan is a while loop and XLA:CPU executes
        # convs inside while loops via a slow reference path (fl/client.py)
        args.chain = 1
        args.blocks = min(args.blocks, 2)

    import jax.numpy as jnp

    from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
        apply_rng_impl)

    rng_impl = apply_rng_impl(args.rng_impl)
    log(f"[bench] prng impl: {rng_impl}")

    from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
        get_federated_data)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
        make_normalizer)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        make_chained_round_fn)
    from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
        get_model, init_params)

    # CPU fallback must actually GET its reduced shapes: on-disk dataset
    # files (full 60k/50k geometry) override synth_* sizes, and the full
    # config on XLA:CPU's conv-in-while slow path runs for hours (r4 find —
    # the driver's round-end bench would wedge). Point the fallback at a
    # nonexistent data dir so the synthetic generator's sizes apply.
    extra = {"use_pallas": args.use_pallas,
             "compile_cache": not args.no_compile_cache,
             "compile_cache_dir": args.compile_cache_dir}
    if args.dtype:
        extra["dtype"] = args.dtype
    if args.train_layout in ("vmap", "megabatch"):
        # a single layout re-points the HEADLINE; 'both' keeps the vmap
        # headline and adds the A/B block below
        extra["train_layout"] = args.train_layout
    if args.health == "off":
        # 'off' re-points the headline; 'both' keeps the (default-on)
        # headline and adds the health_ab block below
        extra["health"] = "off"
    if args.reputation in ("on", "off"):
        # a single setting re-points the HEADLINE; 'both' keeps the
        # auto headline and adds the reputation_ab block below
        extra["reputation"] = args.reputation
    if cpu_fallback:
        extra["data_dir"] = "/nonexistent_use_synthetic_reduced"
    # BASELINE.json configs[1] (fmnist flagship) or configs[3] (resnet9,
    # the MXU-bound north-star shape — VERDICT r3 next #1); shared with
    # scripts/precompile.py via bench_config so the banked program
    # families match what this benchmark dispatches
    cfg = bench_config(args.bench_config, cpu_fallback=cpu_fallback,
                       remat_policy=args.remat_policy,
                       agent_chunk=args.agent_chunk, **extra)
    if args.synth_train_size:
        cfg = cfg.replace(synth_train_size=args.synth_train_size,
                          synth_val_size=max(512,
                                             args.synth_train_size // 10),
                          data_dir="/nonexistent_use_synthetic_reduced")
    if args.agg_mode == "buffered":
        # headline through the buffered tick program (K=m by default —
        # the staleness-0 cadence that matches sync round-for-round)
        cfg = cfg.replace(agg_mode="buffered")
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)

    # persistent XLA cache + AOT executable bank: a warm second run loads
    # the serialized round-block executable and skips XLA entirely
    bank = compile_cache.setup(cfg)
    if bank is not None:
        log(f"[bench] compile cache at {compile_cache.cache_root(cfg)}")

    device = jax.devices()[0]
    log(f"[bench] devices: {jax.devices()}")

    hb.update(phase="data", force=True)
    with tracer.span("bench/data"):
        fed = get_federated_data(cfg)
    model = get_model(cfg.data, cfg.model_arch, cfg.dtype, remat=cfg.remat,
                      remat_policy=cfg.remat_policy)
    norm = make_normalizer(fed.mean, fed.std, fed.raw_is_normalized)
    arrays = (jnp.asarray(fed.train.images), jnp.asarray(fed.train.labels),
              jnp.asarray(fed.train.sizes))
    chain = args.chain

    def measure(mcfg, label="", profile_dir=None, per_block=None):
        """Compile (or load the banked executable) + steady-state
        rounds/sec of mcfg's chained round fn. Returns (params,
        rounds_per_sec, compile_s, cache_info) where compile_s keeps its
        historical meaning (executable acquisition + first block) and
        cache_info carries the cold/warm split.

        Fresh params per call: the chained fn donates its params argument,
        so a prior measurement's buffer cannot be reused."""
        params = init_params(model, fed.train.images.shape[2:],
                             jax.random.PRNGKey(0))
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
            buffered as buffered_mod)
        if buffered_mod.is_buffered(mcfg):
            # buffered mode: the chained scan carries the (params,
            # buffer-state) pair; the AOT example aval below follows
            # automatically (params IS the carry)
            params = (params, buffered_mod.init_state(mcfg, params,
                                                      per_bin=True))
        # chained execution: blocks of rounds fused into one lax.scan
        # dispatch (bit-identical to per-round dispatch; see fl/rounds.py)
        chained = make_chained_round_fn(mcfg, model, norm, *arrays)
        base_key = jax.random.PRNGKey(0)
        call, cache_info = chained, None
        acquire_s = 0.0
        hb.update(phase="compile", compile_in_flight=True, force=True)
        if bank is not None:
            try:
                ab = compile_cache.abstractify
                example = (ab(params), ab(base_key),
                           jax.ShapeDtypeStruct((chain,), jnp.int32)
                           ) + ab(arrays)
                with tracer.span("bench/aot_acquire", label=label):
                    compiled, hit, acquire_s, entry = bank.get_or_compile(
                        chained.family, mcfg, chained.jitted, example)
                data = chained.data
                call = lambda p, k, ids: compiled(p, k, ids, *data)  # noqa: E731
                # cold time comes from THIS run on a miss, and from the
                # banking run's manifest record on a hit — so a warm run
                # can still report the cold/warm ratio it is beating
                cache_info = {
                    "cache_hit": hit,
                    "compile_s_cold": round(float(
                        entry.get("compile_s", acquire_s)), 2),
                    "compile_s_warm": (round(acquire_s, 2) if hit else None),
                }
                log(f"[bench]{label} aot "
                    + ("hit: executable loaded" if hit
                       else "miss: compiled+banked")
                    + f" in {acquire_s:.1f}s")
            except Exception as e:  # bank is an optimization, never fatal
                log(f"[bench]{label} aot unavailable "
                    f"({type(e).__name__}: {e}); jit path")
        # warmup / first block (post-AOT this is pure execution; on the
        # jit path it still includes the trace+compile)
        t0 = time.perf_counter()
        with tracer.span("bench/first_block", label=label):
            params, _ = call(params, base_key, jnp.arange(1, chain + 1))
            jax.block_until_ready(params)
        compile_s = time.perf_counter() - t0 + acquire_s
        log(f"[bench]{label} compile+first {chain}-round block: "
            f"{compile_s:.1f}s")
        hb.update(phase="measure", compile_in_flight=False, force=True)

        n_rounds = args.blocks * chain
        t0 = time.perf_counter()
        with tracer.span("bench/steady_blocks", label=label,
                         blocks=args.blocks):
            for b in range(args.blocks):
                ids = jnp.arange((b + 1) * chain + 1, (b + 2) * chain + 1)
                params, _ = call(params, base_key, ids)
                if per_block is not None:
                    # the events A/B hook: ledger emit + exporter flush
                    # at block cadence, INSIDE the timed window
                    per_block(b, (b + 1) * chain)
            jax.block_until_ready(params)
        elapsed = time.perf_counter() - t0
        rounds_per_sec = n_rounds / elapsed
        log(f"[bench]{label} {n_rounds} rounds in {elapsed:.2f}s "
            f"-> {rounds_per_sec:.3f} rounds/sec steady-state")

        if profile_dir and args.profile_rounds > 0:
            # device-time attribution window (obs/attribution.py): EXTRA
            # steady blocks under the profiler, after the timed ones, so
            # capture overhead never touches the headline figure
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                attribution)
            p_blocks = -(-args.profile_rounds // chain)
            if jax.default_backend() != "tpu":
                # XLA:CPU's profiler records every op thunk of the
                # conv-in-loop path: full-shape CPU rounds serialize
                # multi-minute, multi-GB traces at stop_trace. Useful
                # only on reduced shapes (the CI smoke) — say so.
                log("[bench] WARNING: profiling a non-TPU backend — "
                    "stop_trace serialization can take minutes on "
                    "full-shape CPU rounds (fine on reduced shapes)")
            hb.update(phase="profile", force=True)
            with tracer.span("bench/profile_blocks", blocks=p_blocks):
                jax.profiler.start_trace(profile_dir)
                for b in range(args.blocks, args.blocks + p_blocks):
                    ids = jnp.arange((b + 1) * chain + 1,
                                     (b + 2) * chain + 1)
                    params, _ = call(params, base_key, ids)
                jax.block_until_ready(params)
                jax.profiler.stop_trace()
            attribution.write_capture_meta(profile_dir, {
                "rounds": p_blocks * chain,
                "backend": jax.default_backend(),
                "source": "bench --profile_rounds"})
            log(f"[bench]{label} profiled {p_blocks * chain} extra rounds "
                f"-> {profile_dir}")
        if buffered_mod.is_buffered(mcfg):
            # downstream consumers (eval, FLOP cost analysis) want the
            # bare model params, not the (params, buffer-state) carry
            params = params[0]
        return params, rounds_per_sec, compile_s, cache_info

    params, rounds_per_sec, compile_s, cache_info = measure(
        cfg, profile_dir=(args.profile_trace_dir
                          if args.profile_rounds > 0 else None))

    # device-time attribution of the profiled window + HBM watermarks
    # (obs/attribution.py) — the fields the run report and BENCH_NOTES r7
    # judge; hbm is polled regardless of profiling (None-stats backends
    # simply omit it)
    from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
        attribution as obs_attribution)
    attribution_out = None
    if args.profile_rounds > 0:
        attribution_out = obs_attribution.attribute(args.profile_trace_dir)
        if attribution_out is not None and \
                attribution_out.get("device_present"):
            log(f"[bench] attribution: "
                f"{attribution_out['compute_ms']:.1f} ms compute | "
                f"{attribution_out['collective_ms']:.1f} ms collective "
                f"({100 * attribution_out['collective_frac']:.1f}%) | "
                f"{attribution_out['gap_ms']:.1f} ms gap")
        elif attribution_out is not None:
            log(f"[bench] attribution: "
                f"{attribution_out.get('note', 'no device track')}")
    hbm = obs_attribution.memory_watermarks()

    faults_out = None
    if args.faults:
        # masking-overhead probe (faults/): the same config with 30% client
        # dropout exercises the participation-mask aggregation path; the
        # delta vs the dense 0% run is the cost of mask-aware aggregation
        # (dropped agents still train — shapes are static — so compute
        # doesn't shrink with the electorate)
        r0 = rounds_per_sec
        if cfg.use_pallas:
            # the faults path can't take the fused Pallas server step, so a
            # pallas-on 0% baseline would fold the kernel's win into
            # "masking overhead" — re-measure the baseline unfused
            log("[bench] --faults: re-measuring the 0% baseline without "
                "the Pallas kernel for a like-for-like overhead figure")
            _, r0, _, _ = measure(cfg.replace(use_pallas=False),
                                  label="[faults dropout=0, no pallas]")
        _, r30, c30, _ = measure(
            cfg.replace(dropout_rate=0.3, use_pallas=False),
            label="[faults dropout=0.3]")
        faults_out = {
            "dropout0_rounds_per_sec": round(r0, 4),
            "dropout30_rounds_per_sec": round(r30, 4),
            "masking_overhead_pct": round(100.0 * (1.0 - r30 / r0), 2),
            "dropout30_compile_s": round(c30, 1),
        }
        log(f"[bench] masking overhead at 30% dropout: "
            f"{faults_out['masking_overhead_pct']}%")

    telemetry_out = None
    if args.telemetry != "off":
        # telemetry-overhead probe (obs/telemetry.py): same config with
        # in-jit defense telemetry compiled into the round program; the
        # delta vs the off run is the cost of the extra on-device stats
        # (the headline `value` stays the off number)
        r_base = rounds_per_sec
        if cfg.use_pallas:
            # telemetry falls back off the fused Pallas server step, so a
            # pallas-on baseline would fold the kernel's win into
            # "telemetry overhead" — re-measure unfused
            log("[bench] --telemetry: re-measuring the off baseline "
                "without the Pallas kernel for a like-for-like overhead")
            _, r_base, _, _ = measure(cfg.replace(use_pallas=False),
                                      label="[telemetry off, no pallas]")
        _, r_tel, c_tel, _ = measure(
            cfg.replace(telemetry=args.telemetry, use_pallas=False),
            label=f"[telemetry {args.telemetry}]")
        telemetry_out = {
            "level": args.telemetry,
            "off_rounds_per_sec": round(r_base, 4),
            "on_rounds_per_sec": round(r_tel, 4),
            "overhead_pct": round(100.0 * (1.0 - r_tel / r_base), 2),
            "compile_s": round(c_tel, 1),
        }
        log(f"[bench] telemetry={args.telemetry} overhead: "
            f"{telemetry_out['overhead_pct']}%")

    health_ab_out = None
    if args.health == "both":
        # health-lane overhead A/B (ISSUE 14): same config with the
        # in-jit sentinel compiled OUT of the round program; the on
        # headline vs the off twin is the cost of the lane's reductions
        # (acceptance: <=1% on steady rounds/sec — the sharded scalars
        # pack into the loss psum, so there is no collective delta to
        # pay, only the reduction arithmetic)
        hb.update(phase="health_ab", force=True)
        _, r_hoff, c_hoff, _ = measure(cfg.replace(health="off"),
                                       label="[health off]")
        health_ab_out = {
            "on_rounds_per_sec": round(rounds_per_sec, 4),
            "off_rounds_per_sec": round(r_hoff, 4),
            "overhead_pct": round(
                100.0 * (1.0 - rounds_per_sec / r_hoff), 2),
            "compile_s_off": round(c_hoff, 1),
        }
        log(f"[bench] health-lane overhead: "
            f"{health_ab_out['overhead_pct']}% "
            f"(on {rounds_per_sec:.3f} vs off {r_hoff:.3f} r/s)")

    reputation_ab_out = None
    if args.reputation == "both":
        # reputation-lane overhead A/B (ISSUE 20): same config with the
        # rep_agree + rep_norm client rows compiled OUT of the round
        # program; the on headline vs the off twin is the cost of the
        # two lanes (acceptance: <1% on steady rounds/sec — both rows
        # are device-local reductions riding the existing sign-sum tree
        # and update buffers, so there is no collective delta to pay)
        hb.update(phase="reputation_ab", force=True)
        _, r_roff, c_roff, _ = measure(cfg.replace(reputation="off"),
                                       label="[reputation off]")
        reputation_ab_out = {
            "on_rounds_per_sec": round(rounds_per_sec, 4),
            "off_rounds_per_sec": round(r_roff, 4),
            "overhead_pct": round(
                100.0 * (1.0 - rounds_per_sec / r_roff), 2),
            "compile_s_off": round(c_roff, 1),
        }
        log(f"[bench] reputation-lane overhead: "
            f"{reputation_ab_out['overhead_pct']}% "
            f"(on {rounds_per_sec:.3f} vs off {r_roff:.3f} r/s)")

    events_ab_out = None
    if args.events == "both":
        # ledger+exporter overhead A/B (ISSUE 15): the headline blocks
        # re-measured with a live event ledger and Prometheus textfile
        # exporter serviced once per block — the boundary-cadence cost a
        # service run would pay. Pure host-side IO: the traced program is
        # untouched, so the acceptance (<1% steady rounds/sec) is about
        # write+flush latency hiding under the dispatched block.
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            events as obs_events, export as obs_export)
        hb.update(phase="events_ab", force=True)
        ev_path = "logs/bench_events.jsonl"
        if os.path.exists(ev_path):
            os.remove(ev_path)
        ledger = obs_events.EventLedger(ev_path, run="bench",
                                        corr=obs_events.corr_id("bench"))
        exporter = obs_export.MetricsExporter(
            textfile="logs/bench_metrics.prom", info={"run": "bench"})

        def _per_block(b, rounds_done):
            ledger.emit("bench/block", round=rounds_done, block=b)
            exporter.observe_rounds(rounds_done)
            exporter.set("round", rounds_done)
            exporter.flush()

        _, r_ev, _, _ = measure(cfg, label="[events on]",
                                per_block=_per_block)
        ledger.close()
        exporter.close()
        events_ab_out = {
            "off_rounds_per_sec": round(rounds_per_sec, 4),
            "on_rounds_per_sec": round(r_ev, 4),
            "overhead_pct": round(
                100.0 * (1.0 - r_ev / rounds_per_sec), 2),
        }
        log(f"[bench] ledger+exporter overhead: "
            f"{events_ab_out['overhead_pct']}% "
            f"(off {rounds_per_sec:.3f} vs on {r_ev:.3f} r/s)")

    population_out = None
    if args.population_ladder:
        # population-axis measurement (ISSUE 7): the cohort-sampled path
        # decouples population size from per-round cohort size. Two
        # claims go on the record here: (1) equal-cohort overhead — the
        # flagship config re-run through the cohort program over a
        # label_shards bank (bitwise-identical shards, same [m, ...]
        # shapes; the delta vs the dense headline is pure cohort
        # machinery: in-program sampling + per-round gather/H2D, within
        # 10% by acceptance); (2) the ladder — rounds/sec at each
        # population rung with the SAME cohort size and samples/client
        # (compute-comparable), with host peak RSS + HBM watermarks per
        # rung. ru_maxrss is monotone, so an ascending ladder whose
        # watermark stays flat IS the constant-memory proof.
        import numpy as np

        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            cohort as cohort_mod)
        from defending_against_backdoors_with_robust_learning_rate_tpu.data.prefetch import (
            RoundPrefetcher)
        from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
            get_cohort_data)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
            make_chained_cohort_round_fn, make_cohort_round_fn)

        def measure_cohort(mcfg, label):
            """Steady rounds/sec of mcfg's cohort-sampled program: the
            driver's own prefetch pipeline (data/prefetch.py, depth 1)
            overlaps the bank gather + H2D with the running block, so
            the figure reflects the real round pipeline, not a
            serialized gather."""
            hb.update(phase=f"population{label}", force=True)
            t0 = time.perf_counter()
            with tracer.span("bench/bank", label=label):
                src = get_cohort_data(mcfg)
            bank_s = time.perf_counter() - t0
            bank_bytes = sum(
                os.path.getsize(os.path.join(src.bank.dir, f))
                for f in os.listdir(src.bank.dir))
            params = init_params(model, fed.train.images.shape[2:],
                                 jax.random.PRNGKey(0))
            base_key = jax.random.PRNGKey(0)
            fn = (make_chained_cohort_round_fn(mcfg, model, norm)
                  if chain > 1 else make_cohort_round_fn(mcfg, model, norm))

            def gather_unit(unit):
                ids = [cohort_mod.sample_cohort_host(mcfg, r)[0]
                       for r in unit]
                rows = [src.gather_cohort(i) for i in ids]
                if len(unit) == 1:
                    return tuple(map(jnp.asarray, rows[0]))
                return tuple(jnp.asarray(np.stack([r[k] for r in rows]))
                             for k in range(3))

            n_blocks = args.blocks + 1   # block 0 = compile + warmup
            sched = [tuple(range(b * chain + 1, (b + 1) * chain + 1))
                     for b in range(n_blocks)]
            pre = RoundPrefetcher(gather_unit, sched, depth=1)
            try:
                def run_block(params, b):
                    payload = pre.get(sched[b])
                    if chain > 1:
                        ids = jnp.asarray(sched[b], jnp.int32)
                        return fn(params, base_key, ids, *payload)[0]
                    return fn(params, base_key, jnp.int32(sched[b][0]),
                              *payload)[0]

                hb.update(phase="compile", compile_in_flight=True,
                          force=True)
                t0 = time.perf_counter()
                with tracer.span("bench/cohort_first", label=label):
                    params = run_block(params, 0)
                    jax.block_until_ready(params)
                compile_s = time.perf_counter() - t0
                hb.update(phase="measure", compile_in_flight=False,
                          force=True)
                t0 = time.perf_counter()
                with tracer.span("bench/cohort_steady", label=label,
                                 blocks=args.blocks):
                    for b in range(1, n_blocks):
                        params = run_block(params, b)
                    jax.block_until_ready(params)
                elapsed = time.perf_counter() - t0
            finally:
                pre.close()
            r = args.blocks * chain / elapsed
            log(f"[bench]{label} {args.blocks * chain} rounds in "
                f"{elapsed:.2f}s -> {r:.3f} rounds/sec steady-state "
                f"(bank {bank_bytes / 2**20:.1f} MiB in {bank_s:.1f}s, "
                f"compile+first {compile_s:.1f}s)")
            return r, compile_s, bank_s, bank_bytes

        # (1) equal-cohort A/B on the flagship: same population, same
        # shards (label_shards), same shapes — cohort machinery only.
        # The cohort program always carries the active mask, so it never
        # takes the fused Pallas server step; a pallas-on dense baseline
        # would fold the kernel's win into "cohort overhead" (same
        # re-measure the faults/telemetry probes do)
        r_dense = rounds_per_sec
        if cfg.use_pallas:
            log("[bench] --population_ladder: re-measuring the dense "
                "baseline without the Pallas kernel for a like-for-like "
                "cohort-overhead figure")
            _, r_dense, _, _ = measure(cfg.replace(use_pallas=False),
                                       label="[dense, no pallas]")
        ab_cfg = cfg.replace(cohort_sampled="on",
                             cohort_size=cfg.agents_per_round,
                             partitioner="label_shards",
                             use_pallas=False)
        r_ab, c_ab, _, _ = measure_cohort(
            ab_cfg, f"[cohort K={cfg.num_agents}]")
        population_out = {
            "cohort_size": cfg.agents_per_round,
            "dense_rounds_per_sec": round(r_dense, 4),
            "equal_cohort_rounds_per_sec": round(r_ab, 4),
            "cohort_overhead_pct": round(
                100.0 * (1.0 - r_ab / r_dense), 2),
            "equal_cohort_compile_s": round(c_ab, 1),
            "ladder": [],
        }
        log(f"[bench] equal-cohort overhead vs dense: "
            f"{population_out['cohort_overhead_pct']}%")

        # (2) the population ladder, ascending so the monotone RSS
        # watermark judges flatness. samples_per_client is resolved ONCE
        # (auto would resolve per rung — clip(n/K) shrinks with K — and
        # different max_n per rung would break the rungs'
        # compute-comparability the r9 template relies on); the largest
        # rung's auto value lands on every rung.
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            bank as bank_mod)
        from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
            get_datasets)
        def current_rss_bytes():
            # ru_maxrss is the PROCESS-lifetime peak — the dense headline
            # measured above may dominate it, making a flat peak ladder
            # vacuous. The instantaneous VmRSS per rung is the signal
            # that would actually expose O(population) growth in-process
            # (the CI population-smoke job measures each rung in its own
            # process for the rigorous watermark).
            try:
                with open("/proc/self/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            return int(line.split()[1]) * 1024
            except OSError:
                pass
            return None

        rungs = sorted(int(x) for x in
                       args.population_ladder.split(",") if x.strip())
        base_train, _, _ = get_datasets(cfg)
        if isinstance(base_train, list):
            raise ValueError(
                "the population ladder needs a single base dataset to "
                "index (pre-split per-user data cannot be re-partitioned)")
        ladder_spc = bank_mod.resolve_samples_per_client(
            args.ladder_spc, len(base_train.labels), max(rungs))
        population_out["ladder_samples_per_client"] = ladder_spc
        log(f"[bench] ladder samples/client: {ladder_spc} (same on "
            f"every rung)")
        for pop in rungs:
            rung_cfg = cfg.replace(
                num_agents=pop, cohort_sampled="on",
                cohort_size=cfg.agents_per_round,
                partitioner=args.ladder_partitioner,
                samples_per_client=ladder_spc)
            r, c_s, bank_s, bank_bytes = measure_cohort(
                rung_cfg, f"[population {pop}]")
            rss = obs_attribution.host_watermarks()
            cur = current_rss_bytes()
            if cur is not None:
                rss["host_rss_bytes"] = cur
            rung_hbm = obs_attribution.memory_watermarks()
            row = {"population": pop,
                   "rounds_per_sec": round(r, 4),
                   "compile_s": round(c_s, 1),
                   "bank_build_s": round(bank_s, 1),
                   "bank_bytes": bank_bytes,
                   **rss, **rung_hbm}
            population_out["ladder"].append(row)
            log(f"[bench] rung {pop:,}: {r:.3f} rounds/sec, host RSS "
                f"{(cur or 0) / 2**30:.2f} GiB now / "
                f"{rss.get('host_peak_rss_bytes', 0) / 2**30:.2f} GiB "
                f"peak")

    # analytic performance anatomy (ISSUE 10): FLOPs/round from the model
    # registry's arithmetic — no compile, works on every backend, so the
    # MFU trajectory is tracked on CPU before a TPU session ever runs.
    # One fwd+bwd step ~ 3x the forward (registry docstring convention).
    from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
        flops_per_example)
    peak = peak_tflops(device.device_kind)
    analytic_round = None
    fwd_flops = flops_per_example(cfg.data, cfg.model_arch,
                                  fed.train.images.shape[2:], cfg.n_classes)
    if fwd_flops:
        nb_an = fed.train.images.shape[1] // cfg.bs
        analytic_round = (cfg.agents_per_round * cfg.local_ep * nb_an
                          * cfg.bs * 3.0 * fwd_flops)
        log(f"[bench] analytic {analytic_round/1e12:.2f} TFLOP/round "
            f"({cfg.agents_per_round}x{cfg.local_ep}x{nb_an}x{cfg.bs} "
            f"examples, 3x fwd)")

    def layout_row(r, c_s):
        """Per-layout A/B record: throughput + the analytic-FLOP MFU
        fields (mfu only when the chip's peak is known — on CPU the
        trackable trajectory number is analytic_tflops_per_sec)."""
        row = {"rounds_per_sec": round(r, 4), "compile_s": round(c_s, 1)}
        if analytic_round:
            tps = analytic_round * r / 1e12
            row["analytic_tflops_per_sec"] = round(tps, 3)
            if peak:
                row["mfu"] = round(tps / peak, 4)
        return row

    layout_ab_out = None
    if args.train_layout == "both":
        # train-layout A/B (ISSUE 10): the SAME flagship config through
        # the chained round program under each local-training layout —
        # the vmap headline above is reused as its own cell, megabatch
        # measured fresh (distinct chained_mb program family, its own
        # AOT entry)
        hb.update(phase="train_layout_ab", force=True)
        # the megabatch cell gets ITS OWN capture dir: the headline's
        # --profile_rounds trace above profiled the vmap program, and an
        # attribution labeled megabatch but measured on vmap would lie
        # to the r11 MFU judgment
        mb_profile = (args.profile_trace_dir + "_mb"
                      if args.profile_rounds > 0 else None)
        _, r_mb, c_mb, _ = measure(cfg.replace(train_layout="megabatch"),
                                   label="[train_layout megabatch]",
                                   profile_dir=mb_profile)
        layout_ab_out = {"vmap": layout_row(rounds_per_sec, compile_s),
                         "megabatch": layout_row(r_mb, c_mb),
                         "megabatch_vs_vmap": round(
                             r_mb / rounds_per_sec, 4)}
        if mb_profile:
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                attribution as _attr)
            mb_attr = _attr.attribute(mb_profile)
            if mb_attr is not None:
                # the vmap layout's attribution is the top-level
                # `attribution` field (the headline capture)
                layout_ab_out["megabatch"]["attribution"] = mb_attr
        log(f"[bench] megabatch/vmap throughput ratio: "
            f"{layout_ab_out['megabatch_vs_vmap']:.3f}x")

    # performance anatomy (VERDICT r2 weak #1): FLOPs/round from XLA's own
    # cost analysis of the compiled client step, and MFU against the chip's
    # bf16 peak — "actually fast, or just correct?" on the record
    flops_round = mfu = tflops_sec = None
    try:
        # non-remat twin for the FLOP count (see train_step_flops docstring)
        flops_model = (get_model(cfg.data, cfg.model_arch, cfg.dtype,
                                 remat=False) if cfg.remat else model)
        step_flops = train_step_flops(flops_model, params, norm, cfg,
                                      fed.train.images.shape[2:])
        if step_flops > 0:
            nb = fed.train.images.shape[1] // cfg.bs
            flops_round = (cfg.agents_per_round * cfg.local_ep * nb
                           * step_flops)
            tflops_sec = flops_round * rounds_per_sec / 1e12
            # `peak` computed once beside the analytic block above
            log(f"[bench] {flops_round/1e12:.2f} TFLOP/round (XLA cost "
                f"analysis, {cfg.agents_per_round}x{cfg.local_ep}x{nb} "
                f"steps) -> {tflops_sec:.1f} TFLOP/s")
            if peak:
                mfu = tflops_sec / peak
                log(f"[bench] MFU {100*mfu:.1f}% of {peak:.0f} TFLOP/s "
                    f"bf16 peak ({device.device_kind})")
    except Exception as e:  # cost analysis is informative, never fatal
        log(f"[bench] cost analysis unavailable: {e}")

    # host-sync anatomy: the blocking time per eval boundary that train.py's
    # async metrics drain removes from the round loop's critical path
    # (eval_sync_s - eval_dispatch_s = host wait the driver no longer pays)
    host_sync = None
    hb.update(phase="eval_probe", force=True)
    try:
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
            make_eval_fn, pad_eval_set)
        eval_fn = make_eval_fn(model, norm, cfg.n_classes)
        val = tuple(map(jnp.asarray, pad_eval_set(
            fed.val_images, fed.val_labels, cfg.eval_bs)))
        jax.block_until_ready(eval_fn(params, *val))  # compile outside timing
        t0 = time.perf_counter()
        vl, va, _ = eval_fn(params, *val)
        dispatch_s = time.perf_counter() - t0
        _ = (float(vl), float(va))   # the driver's old inline sync
        sync_s = time.perf_counter() - t0
        host_sync = {"eval_dispatch_s": round(dispatch_s, 4),
                     "eval_sync_s": round(sync_s, 4),
                     "removed_per_eval_s": round(sync_s - dispatch_s, 4)}
        log(f"[bench] eval dispatch {dispatch_s*1e3:.1f}ms vs sync "
            f"{sync_s*1e3:.1f}ms -> async metrics hide "
            f"{(sync_s - dispatch_s)*1e3:.1f}ms per eval boundary")
    except Exception as e:  # informative, never fatal
        log(f"[bench] host-sync probe unavailable: {e}")

    agg_mode_ab = None
    if args.agg_mode == "both":
        # buffered-async A/B (ISSUE 12): (1) buffered at K=m, staleness 0
        # — the pure mode overhead (acceptance: ticks/sec within 3% of
        # sync rounds/sec; the fold arithmetic is the only delta); (2) at
        # 30%/50% straggler rates, sync rounds/sec (the barrier pays the
        # latency on the simulated clock) vs buffered ticks/sec at
        # K=m/2 — the production-shape comparison the r13 notes judge.
        hb.update(phase="agg_mode_ab", force=True)
        _, r_buf, c_buf, _ = measure(cfg.replace(agg_mode="buffered"),
                                     label="[agg_mode buffered K=m]")
        agg_mode_ab = {
            "sync": {"rounds_per_sec": round(rounds_per_sec, 4)},
            "buffered": {"ticks_per_sec": round(r_buf, 4),
                         "compile_s": round(c_buf, 1)},
            "buffered_vs_sync": round(r_buf / rounds_per_sec, 4)}
        for rate in (0.3, 0.5):
            scfg = cfg.replace(straggler_rate=rate)
            _, r_s, _, _ = measure(scfg,
                                   label=f"[sync straggler={rate}]")
            _, r_b, _, _ = measure(
                scfg.replace(agg_mode="buffered",
                             async_buffer_k=max(
                                 1, cfg.agents_per_round // 2)),
                label=f"[buffered K=m/2 straggler={rate}]")
            agg_mode_ab[f"straggler_{rate}"] = {
                "sync_rounds_per_sec": round(r_s, 4),
                "buffered_ticks_per_sec": round(r_b, 4)}
        log(f"[bench] buffered/sync throughput ratio at K=m: "
            f"{agg_mode_ab['buffered_vs_sync']:.3f}x")

    tenancy_ab_out = None
    if args.tenants >= 2:
        # multi-tenant A/B (ISSUE 13, service/tenancy.py): the SAME
        # 16-cell shape-compatible cell list (seeds x RLR thresholds —
        # pure per-tenant knobs) through the serial queue and the
        # tenant-packed queue at --tenants E. Each arm reports wall +
        # cells/hour; the headline is the packed/serial speedup (the
        # ROADMAP target is >10x on TPU via the banked *_mt families).
        hb.update(phase="tenancy_ab", force=True)
        from defending_against_backdoors_with_robust_learning_rate_tpu.service.queue import (
            run_queue)
        thr_ab = cfg.robustLR_threshold or 4
        ab_cells = [{"name": f"s{s}_t{t}",
                     "overrides": {"seed": s, "robustLR_threshold": t}}
                    for t in (0, thr_ab) for s in range(8)]
        ab_cfg = cfg.replace(rounds=2 * chain, snap=chain,
                             tensorboard=False, profile_rounds=0)
        tenancy_ab_out = {"cells": len(ab_cells), "tenants": args.tenants,
                          "rounds_per_cell": ab_cfg.rounds}
        for arm, E in (("serial", 0), ("packed", args.tenants)):
            arm_cfg = ab_cfg.replace(log_dir=os.path.join(
                cfg.log_dir, "tenancy_ab", arm))
            t_arm = time.perf_counter()
            rows = run_queue(
                arm_cfg,
                [dict(c, overrides=dict(c["overrides"]))
                 for c in ab_cells],
                results_path=os.path.join(arm_cfg.log_dir,
                                          "queue_results.jsonl"),
                tenants=E)
            wall = time.perf_counter() - t_arm
            ok = sum(r["ok"] for r in rows)
            tenancy_ab_out[arm] = {
                "ok": ok, "wall_s": round(wall, 2),
                "cells_per_hour": round(3600.0 * ok / max(wall, 1e-9),
                                        2)}
        tenancy_ab_out["speedup"] = round(
            tenancy_ab_out["packed"]["cells_per_hour"]
            / max(tenancy_ab_out["serial"]["cells_per_hour"], 1e-9), 3)
        log(f"[bench] tenancy A/B: serial "
            f"{tenancy_ab_out['serial']['cells_per_hour']:.1f} vs packed "
            f"{tenancy_ab_out['packed']['cells_per_hour']:.1f} cells/hour"
            f" ({tenancy_ab_out['speedup']:.2f}x at E={args.tenants})")

    agg_ab_out = None
    if args.agg_layout:
        # sharded-layout A/B (ISSUE 8): the SAME flagship config through
        # the shard_map round program under each aggregation layout, on
        # the largest local mesh dividing m. Per-round dispatch (no
        # chain: XLA:CPU's conv-in-while slow path would swamp the
        # collective delta on the fallback host); each layout reports
        # steady rounds/sec plus its jaxpr + compiled-HLO collective
        # counts, so the A/B carries the communication-plan evidence
        # next to the throughput it buys.
        from defending_against_backdoors_with_robust_learning_rate_tpu.analysis import (
            jaxpr_lint)
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
            make_mesh, pick_agent_mesh_size)
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
            make_sharded_round_fn)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
            _pallas_applicable)
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
            _bucket_applicable)
        d = pick_agent_mesh_size(0, cfg.agents_per_round)
        layouts = (("leaf", "bucket") if args.agg_layout == "both"
                   else (args.agg_layout,))
        if d <= 1:
            agg_ab_out = {"note": f"needs >1 devices dividing "
                                  f"agents_per_round={cfg.agents_per_round}"
                                  f" (have {jax.device_count()})"}
            log(f"[bench] agg-layout A/B skipped: {agg_ab_out['note']}")
        elif _pallas_applicable(cfg) or not _bucket_applicable(
                cfg.replace(agg_layout="bucket")):
            # the bucket flag would be a no-op here (the fused pallas
            # step wins the plan precedence exactly when
            # _pallas_applicable holds; non-avg/sign rules keep their
            # transpose plans) — measuring two identical programs as an
            # A/B would be a lie
            agg_ab_out = {"note": f"config never buckets "
                                  f"(pallas={_pallas_applicable(cfg)}, "
                                  f"aggr={cfg.aggr!r}); both layouts "
                                  f"would trace the same program"}
            log(f"[bench] agg-layout A/B skipped: {agg_ab_out['note']}")
        else:
            mesh = make_mesh(d)
            agg_ab_out = {"mesh": d}
            n_rounds = args.blocks * chain
            hb.update(phase="agg_ab", force=True)
            for lay in layouts:
                lcfg = cfg.replace(agg_layout=lay)
                sp = init_params(model, fed.train.images.shape[2:],
                                 jax.random.PRNGKey(0))
                fn = make_sharded_round_fn(lcfg, model, norm, mesh,
                                           *arrays)
                ab = compile_cache.abstractify
                ex = (ab(sp), ab(jax.random.PRNGKey(0))) + arrays
                closed = compile_cache.trace_program(fn.jitted, ex)
                counts = {k: v for k, v in
                          jaxpr_lint.collective_counts(closed).items()
                          if v}
                # ONE compile per layout: the Compiled that yields the
                # HLO counts also drives the measurement (calling the
                # bound fn instead would jit-compile the same program a
                # second time — tens of seconds each on the CPU fallback)
                compiled = compile_cache.lower_program(
                    fn.jitted, ex).compile()
                hcounts = jaxpr_lint.hlo_collective_counts(
                    compiled.as_text())
                with tracer.span("bench/agg_ab_first", layout=lay):
                    key = jax.random.PRNGKey(1)
                    sp, _ = compiled(sp, key, *arrays)
                    jax.block_until_ready(sp)
                t0 = time.perf_counter()
                with tracer.span("bench/agg_ab_steady", layout=lay,
                                 rounds=n_rounds):
                    for r in range(n_rounds):
                        key = jax.random.fold_in(jax.random.PRNGKey(1), r)
                        sp, _ = compiled(sp, key, *arrays)
                    jax.block_until_ready(sp)
                rps = n_rounds / (time.perf_counter() - t0)
                agg_ab_out[lay] = {
                    "rounds_per_sec": round(rps, 4),
                    "jaxpr_collectives": counts,
                    "hlo_collectives": hcounts,
                }
                log(f"[bench] agg_layout={lay}: {rps:.3f} rounds/sec on "
                    f"the {d}-way mesh | jaxpr {counts} | hlo {hcounts}")
            if len(layouts) == 2:
                agg_ab_out["bucket_vs_leaf"] = round(
                    agg_ab_out["bucket"]["rounds_per_sec"]
                    / agg_ab_out["leaf"]["rounds_per_sec"], 4)
                log(f"[bench] bucket/leaf throughput ratio: "
                    f"{agg_ab_out['bucket_vs_leaf']:.3f}x")

    vs_baseline = None
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")
    if os.path.exists(base_path) and args.bench_config == "fmnist":
        # the measured torch baseline is the CNN_MNIST batch step; it does
        # not transfer to ResNet-9 (a model the reference doesn't have), so
        # the resnet9 config omits the key entirely rather than emitting a
        # fake 1.0x
        with open(base_path) as f:
            base = json.load(f)
        batches_per_agent = fed.train.images.shape[1] // cfg.bs
        ref_round_sec = (cfg.agents_per_round * cfg.local_ep *
                         batches_per_agent * base["sec_per_batch_step"])
        vs_baseline = rounds_per_sec * ref_round_sec
        log(f"[bench] reference-semantics round would take "
            f"{ref_round_sec:.1f}s on this host's CPU -> "
            f"speedup {vs_baseline:.1f}x")

    out = {"metric": "fl_rounds_per_sec",
           "value": round(rounds_per_sec, 4),
           "unit": "rounds/sec",
           "compile_s": round(compile_s, 1),
           "chain": chain,
           # blocks*chain = steady rounds: obs/explain.py normalizes the
           # span totals per round with it when diffing two artifacts
           "blocks": args.blocks,
           "rng_impl": rng_impl,
           "bench_config": args.bench_config,
           "dtype": cfg.dtype,
           "device": str(device)}
    if cache_info is not None:
        # cold-vs-warm compile persistence (utils/compile_cache.py): a
        # second run on a populated cache reports cache_hit true and
        # compile_s_warm (executable deserialize) << compile_s_cold
        out["cache_hit"] = cache_info["cache_hit"]
        out["compile_s_cold"] = cache_info["compile_s_cold"]
        if cache_info["compile_s_warm"] is not None:
            out["compile_s_warm"] = cache_info["compile_s_warm"]
    if host_sync is not None:
        out["host_sync"] = host_sync
    if ignored_flags:
        out["ignored_flags"] = ignored_flags
    if vs_baseline is not None:
        # only when a comparable measured baseline exists (fmnist config);
        # resnet9 has no reference counterpart, so no 1.0x placeholder
        out["vs_baseline"] = round(vs_baseline, 2)
    out["train_layout"] = cfg.train_layout
    if flops_round is not None:
        out["tflop_per_round"] = round(flops_round / 1e12, 4)
        out["tflops_per_sec"] = round(tflops_sec, 2)
    if analytic_round is not None:
        # the compile-free MFU trajectory (ISSUE 10): analytic FLOPs from
        # the model registry, trackable on CPU before any TPU session
        out["analytic_tflop_per_round"] = round(analytic_round / 1e12, 4)
        out["analytic_tflops_per_sec"] = round(
            analytic_round * rounds_per_sec / 1e12, 3)
        if mfu is None and peak:
            # cost analysis unavailable (some backends) — the analytic
            # count still yields the MFU figure
            mfu = analytic_round * rounds_per_sec / 1e12 / peak
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    if layout_ab_out is not None:
        out["train_layout_ab"] = layout_ab_out
    if faults_out is not None:
        out["faults"] = faults_out
    if telemetry_out is not None:
        out["telemetry"] = telemetry_out
    out["health"] = cfg.health
    if health_ab_out is not None:
        out["health_ab"] = health_ab_out
    out["reputation"] = cfg.reputation
    if reputation_ab_out is not None:
        out["reputation_ab"] = reputation_ab_out
    if events_ab_out is not None:
        out["events_ab"] = events_ab_out
    if population_out is not None:
        out["population"] = population_out
    if attribution_out is not None:
        out["attribution"] = attribution_out
    if agg_ab_out is not None:
        out["agg_layout_ab"] = agg_ab_out
    out["agg_mode"] = cfg.agg_mode
    if agg_mode_ab is not None:
        out["agg_mode_ab"] = agg_mode_ab
    if tenancy_ab_out is not None:
        out["tenancy_ab"] = tenancy_ab_out
    if hbm:
        out["hbm"] = hbm
    # per-phase span aggregates (obs/spans.py): where this bench's wall
    # time actually went — probe vs data vs acquire vs blocks
    out["spans"] = tracer.aggregates()
    if cpu_fallback:
        # rounds are 10x smaller than the TPU config: value is NOT
        # comparable to TPU rows, vs_baseline (per-batch-normalized) is
        out["reduced_shapes"] = True
    if args.synth_train_size:
        out["synth_override"] = args.synth_train_size
    if backend_note:
        out["backend_note"] = backend_note
    hb.close("done")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
